"""Byzantine no-fork commits: the malicious-writer fault-injection drill.

The reference's L0 guarantee is PBFT's: every state mutation executes on
all 4 chain nodes and binds only with a 2f+1 quorum, so one arbitrarily
faulty node cannot fork history or fabricate state (README.md:162-183).
These tests ARE that property for the commit-certificate layer (comm.bft):

- a hostile writer that forges a score row (no committee signature),
  silently drops an acknowledged upload, or forks history (different ops
  to different validators at one position) FAILS certification and its
  state is rejected by certificate-checking clients;
- while f = bft_fault_tolerance(4) = 1 crashed-or-lying validator is
  tolerated and the honest path — including writer failover — stays green.
"""

import hashlib
import struct
import threading
import time
import warnings

import numpy as np
import pytest

from bflc_demo_tpu.comm.bft import (CertificateAssembler, ValidatorClient,
                                    ValidatorNode, cert_payload,
                                    count_valid_sigs, next_head,
                                    provision_validators,
                                    verify_certificate,
                                    verify_certificate_sigs)
from bflc_demo_tpu.comm.failover import FailoverClient, Standby
from bflc_demo_tpu.comm.identity import (Wallet, _op_bytes,
                                         provision_wallets)
from bflc_demo_tpu.comm.ledger_service import LedgerServer
from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
from bflc_demo_tpu.protocol import (CommitCertificate, ProtocolConfig,
                                    bft_fault_tolerance, bft_quorum)
from bflc_demo_tpu.utils.serialization import pack_pytree

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)

N_VALIDATORS = 4                # the reference's 4-node geometry (f=1)
QUORUM = bft_quorum(N_VALIDATORS)


def _init_blob():
    return pack_pytree({"W": np.zeros((5, 2), np.float32),
                        "b": np.zeros((2,), np.float32)})


def _delta_blob(v):
    return pack_pytree({"W": np.full((5, 2), v, np.float32),
                        "b": np.zeros((2,), np.float32)})


def _sign(w, kind, epoch, payload):
    return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()


def _mk_validators(n=N_VALIDATORS, seed=b"bft-drill-01"):
    vwallets, vkeys = provision_validators(n, seed)
    # peer keys provisioned, as in every production deployment
    # (process_runtime) — certificate-led resync/backlog need them
    nodes = [ValidatorNode(CFG, w, i, validator_keys=vkeys)
             for i, w in enumerate(vwallets)]
    for v in nodes:
        v.start()
    eps = [(v.host, v.port) for v in nodes]
    return nodes, eps, vkeys


def _register_all(client, wallets):
    for w in wallets:
        r = client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=_sign(w, "register", 0, b""))
        assert r["ok"] or r["status"] in ("ALREADY_REGISTERED",
                                          "DUPLICATE"), r


def _drive_round(client, wallets, epoch):
    committee = set(client.request("committee")["committee"])
    trainers = [w for w in wallets if w.address not in committee]
    for i, w in enumerate(trainers[: CFG.needed_update_count]):
        blob = _delta_blob(float(i + 1) * 0.1 + epoch)
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", 10 + i, 1.0)
        r = client.request("upload", addr=w.address, blob=blob.hex(),
                           hash=digest.hex(), n=10 + i, cost=1.0,
                           epoch=epoch,
                           tag=_sign(w, "upload", epoch, payload))
        assert r["ok"] or r["status"] == "DUPLICATE", r
    n_up = CFG.needed_update_count
    for j, w in enumerate([w for w in wallets if w.address in committee]):
        scores = [0.5 + 0.01 * (j + u) for u in range(n_up)]
        payload = struct.pack(f"<{n_up}d", *scores)
        r = client.request("scores", addr=w.address, epoch=epoch,
                           scores=scores,
                           tag=_sign(w, "scores", epoch, payload))
        assert r["ok"] or r["status"] in ("DUPLICATE", "WRONG_EPOCH"), r


class TestQuorumGeometry:
    def test_reference_geometry(self):
        # the reference chain: 4 nodes, one arbitrary fault tolerated
        assert bft_fault_tolerance(4) == 1
        assert bft_quorum(4) == 3

    def test_general_geometry(self):
        assert [bft_fault_tolerance(n) for n in (1, 2, 3, 4, 7, 10)] == \
            [0, 0, 0, 1, 2, 3]
        for n in (1, 2, 3, 4, 7, 10):
            f, q = bft_fault_tolerance(n), bft_quorum(n)
            assert q == n - f
            # any two quorums intersect in >= f+1 validators
            assert 2 * q - n >= f + 1

    def test_rejects_empty(self):
        with pytest.raises(ValueError):
            bft_fault_tolerance(0)


class TestValidateWithoutApply:
    """The ledger hook validators build on: deterministic dry-run of the
    full guard set, observably mutation-free."""

    def _fingerprint(self, led):
        return (led.log_size(), led.log_head(), led.epoch,
                led.num_registered, led.update_count, led.score_count,
                led.round_closed, led.generation)

    def test_valid_and_invalid_probe_leave_state_untouched(self):
        led = make_ledger(CFG, backend="python")
        led.register_node("0x" + "aa" * 20)
        probe = make_ledger(CFG, backend="python")
        probe.register_node("0x" + "bb" * 20)
        valid_op = probe.log_op(0)
        before = self._fingerprint(led)
        assert led.validate_op(valid_op) == LedgerStatus.OK
        assert self._fingerprint(led) == before
        # duplicate register: guard rejects, state still untouched
        assert led.validate_op(led.log_op(0)) == \
            LedgerStatus.ALREADY_REGISTERED
        assert led.validate_op(b"") == LedgerStatus.BAD_ARG
        assert self._fingerprint(led) == before
        # the probed op still applies for real afterwards
        assert led.apply_op(valid_op) == LedgerStatus.OK
        assert led.num_registered == 2

    def test_native_backend_agrees(self):
        from bflc_demo_tpu.ledger import bindings
        if not bindings.native_available():
            pytest.skip("native ledger unavailable")
        py = make_ledger(CFG, backend="python")
        nat = make_ledger(CFG, backend="native")
        ops = []
        scratch = make_ledger(CFG, backend="python")
        for i in range(3):
            scratch.register_node(f"0x{i:040x}")
            ops.append(scratch.log_op(i))
        for led in (py, nat):
            for op in ops[:2]:
                assert led.apply_op(op) == LedgerStatus.OK
        for op in (ops[2], ops[0], b"\xff"):
            assert py.validate_op(op) == nat.validate_op(op)
        assert py.log_head() == nat.log_head()


class TestCertificateAlgebra:
    """Pure certificate construction/verification — no sockets."""

    def _cert_for(self, op, index=0, prev=b"\0" * 32, keys_n=N_VALIDATORS,
                  signers=None, seed=b"alg-1"):
        vwallets, vkeys = provision_validators(keys_n, seed)
        head = next_head(prev, op)
        payload = cert_payload(index, prev, op, head)
        sigs = {i: w.sign(payload) for i, w in enumerate(vwallets)
                if signers is None or i in signers}
        cert = CommitCertificate(index=index, prev_head=prev,
                                 op_hash=hashlib.sha256(op).digest(),
                                 new_head=head, sigs=sigs)
        return cert, vkeys

    def test_full_quorum_verifies(self):
        op = b"\x01" + struct.pack("<q", 3) + b"abc"
        cert, keys = self._cert_for(op)
        assert verify_certificate(cert, index=0, prev_head=b"\0" * 32,
                                  op=op, quorum=QUORUM,
                                  validator_keys=keys)
        assert count_valid_sigs(cert, keys) == N_VALIDATORS
        # wire round-trip preserves everything
        again = CommitCertificate.from_wire(cert.to_wire())
        assert verify_certificate_sigs(again.to_wire(), QUORUM, keys)

    def test_thin_and_tampered_certificates_fail(self):
        op = b"\x01" + struct.pack("<q", 3) + b"abc"
        cert, keys = self._cert_for(op, signers={0, 1})   # 2 < 3
        assert not verify_certificate(cert, index=0, prev_head=b"\0" * 32,
                                      op=op, quorum=QUORUM,
                                      validator_keys=keys)
        full, keys = self._cert_for(op)
        # wrong op / wrong position / wrong prefix all break the binding
        assert not verify_certificate(full, index=0, prev_head=b"\0" * 32,
                                      op=op + b"x", quorum=QUORUM,
                                      validator_keys=keys)
        assert not verify_certificate(full, index=1, prev_head=b"\0" * 32,
                                      op=op, quorum=QUORUM,
                                      validator_keys=keys)
        assert not verify_certificate(full, index=0, prev_head=b"\x07" * 32,
                                      op=op, quorum=QUORUM,
                                      validator_keys=keys)
        # signatures by NON-provisioned validators count for nothing
        _, other_keys = provision_validators(N_VALIDATORS, b"other-seed")
        assert count_valid_sigs(full, other_keys) == 0
        # forged sig bytes don't verify; malformed wire never raises
        forged = CommitCertificate(
            index=full.index, prev_head=full.prev_head,
            op_hash=full.op_hash, new_head=full.new_head,
            sigs={i: b"\x00" * 64 for i in range(N_VALIDATORS)})
        assert count_valid_sigs(forged, keys) == 0
        assert not verify_certificate_sigs({"garbage": 1}, QUORUM, keys)
        assert not verify_certificate_sigs(None, QUORUM, keys)


class TestHonestPathCertifies:
    """Green path: the full protocol round certifies op-by-op, replicas
    agree, and the fleet tolerates f=1 crashed or lying validators."""

    def _run(self, kill_validator=False, lie_validator=False):
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"bft-honest-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-honest-01")
        if lie_validator:
            # validator 3 signs with a key nobody provisioned: its votes
            # verify against nothing — a liar, structurally
            nodes[3].wallet = Wallet.from_seed(b"liar")
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python",
                           bft_validators=eps, bft_keys=vkeys,
                           bft_timeout_s=8.0)
        srv.start()
        client = FailoverClient([(srv.host, srv.port)], timeout_s=20.0,
                                bft_keys=vkeys)
        try:
            if kill_validator:
                nodes[3].close()
            _register_all(client, wallets)
            # DUPLICATE-class acks carry the certificate of the ORIGINAL
            # op (request->op binding): the cert-checking client accepts
            # this retry only because the server attached the right one
            w0 = wallets[0]
            r = client.request("register", addr=w0.address,
                              pubkey=w0.public_bytes.hex(),
                              tag=_sign(w0, "register", 0, b""))
            assert r["status"] in ("DUPLICATE", "ALREADY_REGISTERED"), r
            _drive_round(client, wallets, epoch=0)
            info = client.request("info")
            assert info["epoch"] == 1
            assert info["certified_size"] == info["log_size"]
            live = nodes[:3] if kill_validator else nodes
            for v in live:
                assert v.ledger.log_size() == info["log_size"]
                assert v.ledger.log_head().hex() == info["log_head"]
            return info
        finally:
            client.close()
            srv.close()
            for v in nodes:
                v.close()

    def test_round_certifies_and_replicas_agree(self):
        self._run()

    def test_one_crashed_validator_tolerated(self):
        self._run(kill_validator=True)

    def test_one_lying_validator_tolerated(self):
        self._run(lie_validator=True)

    def test_quorum_loss_blocks_acks(self):
        """With TWO validators down (> f), nothing certifies: the writer
        answers CERT_TIMEOUT and a certificate-checking client never
        accepts the state — safety degrades to unavailability, not to
        uncertified acks."""
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"bft-unavail-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-unavail-01")
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python",
                           bft_validators=eps, bft_keys=vkeys,
                           bft_timeout_s=1.0)
        srv.start()
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        c = CoordinatorClient(srv.host, srv.port, timeout_s=20.0)
        try:
            nodes[2].close()
            nodes[3].close()
            w = wallets[0]
            r = c.request("register", addr=w.address,
                          pubkey=w.public_bytes.hex(),
                          tag=_sign(w, "register", 0, b""))
            assert not r["ok"] and r["status"] == "CERT_TIMEOUT", r
        finally:
            c.close()
            srv.close()
            for v in nodes:
                v.close()


class _HostileWriter:
    """A Byzantine writer talking straight to the validator fleet: it
    holds real client traffic (so it can build a plausible chain) but
    tries to bind ops the clients never signed."""

    def __init__(self, eps, vkeys, quorum=QUORUM):
        self.assembler = CertificateAssembler(eps, vkeys, quorum,
                                              timeout_s=5.0)
        self.ledger = make_ledger(CFG, backend="python")
        self.auth = {}                  # index -> auth dict

    def close(self):
        self.assembler.close()

    def head(self):
        return (self.ledger.log_head() if self.ledger.log_size()
                else b"\0" * 32)

    def append_and_certify(self, build_op, auth):
        """build_op mutates self.ledger (appending one op); returns the
        certificate or None."""
        prev = self.head()
        build_op()
        i = self.ledger.log_size() - 1
        op = self.ledger.log_op(i)
        self.auth[i] = auth
        self.assembler.backlog_fn = \
            lambda j: (self.ledger.log_op(j), self.auth.get(j))
        return self.assembler.certify(i, op, auth, prev)


class TestByzantineDrill:
    """The fault-injection drill: forged score rows, dropped uploads and
    forked appends must fail certification."""

    def _writer_with_round_staged(self, eps, vkeys, wallets):
        """A hostile writer that has honestly bound registrations and 3
        uploads (it holds the clients' real signed requests), leaving the
        chain one score row away from aggregation — maximum temptation."""
        hw = _HostileWriter(eps, vkeys)
        for w in wallets:
            cert = hw.append_and_certify(
                lambda w=w: hw.ledger.register_node(w.address),
                {"tag": _sign(w, "register", 0, b""),
                 "pubkey": w.public_bytes.hex()})
            assert cert is not None, "honest register must certify"
        committee = set(hw.ledger.committee())
        trainers = [w for w in wallets if w.address not in committee]
        for i, w in enumerate(trainers[:3]):
            blob = _delta_blob(0.1 * (i + 1))
            digest = hashlib.sha256(blob).digest()
            payload = digest + struct.pack("<qd", 10 + i, 1.0)
            cert = hw.append_and_certify(
                lambda w=w, d=digest, i=i: hw.ledger.upload_local_update(
                    w.address, d, 10 + i, 1.0, 0),
                {"tag": _sign(w, "upload", 0, payload),
                 "n": 10 + i, "cost": 1.0})
            assert cert is not None, "honest upload must certify"
        return hw, committee

    def test_forged_score_row_fails_certification(self):
        """The headline attack (VERDICT r5 missing #1): the writer
        fabricates a committee member's score row.  Every honest
        validator re-checks the member's Ed25519 tag against its own
        directory and refuses; no quorum, no certificate — the forged
        row cannot bind, exactly PBFT's property."""
        wallets, _ = provision_wallets(CFG.client_num, b"bft-forge-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-forge-01")
        hw = None
        try:
            hw, committee = self._writer_with_round_staged(eps, vkeys,
                                                           wallets)
            member = next(w for w in wallets if w.address in committee)
            fake_scores = [1.0, 1.0, 1.0]      # fabricated: boost everyone
            payload = struct.pack("<3d", *fake_scores)
            forged_tag = Wallet.from_seed(b"the-writer-itself").sign(
                _op_bytes("scores", member.address, 0, payload)).hex()
            size_before = [v.ledger.log_size() for v in nodes]
            cert = hw.append_and_certify(
                lambda: hw.ledger.upload_scores(member.address, 0,
                                                fake_scores),
                {"tag": forged_tag, "scores": fake_scores})
            assert cert is None, \
                "a forged score row gathered a certificate"
            # no validator applied it either — their replicas hold the
            # honest prefix only
            assert [v.ledger.log_size() for v in nodes] == size_before
            for v in nodes:
                assert v.ledger.score_count == 0
            # control: the member's REAL signature certifies immediately,
            # so the refusal above was the forged tag and nothing else
            real_tag = _sign(member, "scores", 0, payload)
            # drop the locally-applied-but-refused forged op first
            hw.ledger = _rollback_clone(hw.ledger,
                                        upto=hw.ledger.log_size() - 1)
            cert = hw.append_and_certify(
                lambda: hw.ledger.upload_scores(member.address, 0,
                                                fake_scores),
                {"tag": real_tag, "scores": fake_scores})
            assert cert is not None
        finally:
            if hw is not None:
                hw.close()
            for v in nodes:
                v.close()

    def test_forked_append_cannot_gather_quorum(self):
        """Equivocation: the writer shows op X to validators {0,1} and op
        Y to {2,3} at the same chain position.  Each validator signs at
        most one op per position, so neither branch reaches 2f+1 — and
        every validator answers CONFLICT for the other branch afterwards.
        """
        wallets, _ = provision_wallets(CFG.client_num, b"bft-fork-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-fork-01")
        try:
            # two individually-VALID ops for position 0
            forks = []
            for w in wallets[:2]:
                led = make_ledger(CFG, backend="python")
                led.register_node(w.address)
                forks.append((led.log_op(0),
                              {"tag": _sign(w, "register", 0, b""),
                               "pubkey": w.public_bytes.hex()}))
            half = [eps[:2], eps[2:]]
            sigs = [{}, {}]
            for branch, ((op, auth), eps_half) in enumerate(
                    zip(forks, half)):
                asm = CertificateAssembler(eps_half, vkeys, 1,
                                           timeout_s=5.0)
                cert = asm.certify(0, op, auth, b"\0" * 32)
                asm.close()
                assert cert is not None        # each half signs its branch
                sigs[branch] = cert.sigs
            # neither branch can reach the quorum: 2 sigs each, need 3
            for branch, (op, _) in enumerate(forks):
                cert = CommitCertificate(
                    index=0, prev_head=b"\0" * 32,
                    op_hash=hashlib.sha256(op).digest(),
                    new_head=next_head(b"\0" * 32, op),
                    sigs=sigs[branch])
                assert count_valid_sigs(cert, vkeys) == 2 < QUORUM
                assert not verify_certificate(
                    cert, index=0, prev_head=b"\0" * 32, op=op,
                    quorum=QUORUM, validator_keys=vkeys)
            # cross-asking flips nothing: every validator refuses the op
            # it did NOT sign (CONFLICT), so the writer cannot top up
            for (op, auth), eps_half in zip(forks, reversed(half)):
                for ep in eps_half:
                    vc = ValidatorClient(ep, timeout_s=5.0)
                    r = vc.request("bft_validate", i=0, op=op.hex(),
                                   auth=auth)
                    vc.close()
                    assert not r.get("ok") and \
                        r.get("status") == "CONFLICT", r
        finally:
            for v in nodes:
                v.close()

    def test_dropped_upload_ack_is_rejected_by_the_client(self):
        """A writer that swallows an upload (never appends it) cannot
        fake the ack: without a certificate the ack is refused outright,
        and replaying a REAL certificate it once earned for a different
        op fails the op binding — either way the certificate-checking
        client treats the forged 'ok' like a dead endpoint."""
        vwallets, vkeys = provision_validators(N_VALIDATORS, b"bft-drop-01")

        # mint one GENUINE certificate (an honestly-bound register op) for
        # the writer to replay on its forged acks
        nodes = [ValidatorNode(CFG, w, i, require_auth=False)
                 for i, w in enumerate(vwallets)]
        for v in nodes:
            v.start()
        asm = CertificateAssembler([(v.host, v.port) for v in nodes],
                                   vkeys, QUORUM, timeout_s=5.0)
        led = make_ledger(CFG, backend="python")
        led.register_node("0x" + "ee" * 20)
        stolen = asm.certify(0, led.log_op(0), None, b"\0" * 32)
        asm.close()
        for v in nodes:
            v.close()
        assert stolen is not None

        class _DroppingServer(LedgerServer):
            # Byzantine behavior: claim success, append nothing — first
            # bare, then dressed up with the stolen (quorum-valid but
            # wrong-op) certificate
            replay_cert = None

            def _dispatch(self, method, m):
                if method == "upload":
                    r = {"ok": True, "status": "OK"}
                    if self.replay_cert is not None:
                        r["cert"] = self.replay_cert
                    return r
                return super()._dispatch(method, m)

        srv = _DroppingServer(CFG, _init_blob(), require_auth=False,
                              stall_timeout_s=60.0,
                              ledger_backend="python")
        srv.start()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")     # single endpoint, no keys
            client = FailoverClient([(srv.host, srv.port)], timeout_s=5.0,
                                    max_cycles=2, bft_keys=vkeys)
        try:
            blob = _delta_blob(1.0)
            digest = hashlib.sha256(blob).digest()
            # no certificate at all: refused
            with pytest.raises(ConnectionError, match="certificate"):
                client.request("upload", addr="0x" + "aa" * 20,
                               blob=blob.hex(), hash=digest.hex(), n=10,
                               cost=1.0, epoch=0)
            # a REPLAYED genuine certificate (valid quorum sigs, wrong
            # op): the op binding kills it
            type(srv).replay_cert = stolen.to_wire()
            with pytest.raises(ConnectionError, match="certificate"):
                client.request("upload", addr="0x" + "aa" * 20,
                               blob=blob.hex(), hash=digest.hex(), n=10,
                               cost=1.0, epoch=0)
            assert srv.ledger.update_count == 0     # really dropped
        finally:
            type(srv).replay_cert = None
            client.close()
            srv.close()

    def test_standby_rejects_uncertified_append(self):
        """A standby provisioned with validator keys refuses to replicate
        ops that arrive without a quorum certificate — a Byzantine writer
        cannot turn honest replicas into accomplices."""
        _, vkeys = provision_validators(N_VALIDATORS, b"bft-sb-01")
        # a writer with NO validators: its stream carries no certs
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        c = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
        standby = None
        try:
            assert c.request("register", addr="0x" + "aa" * 20)["ok"]
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")     # wallet-less standby
                standby = Standby(CFG, [(srv.host, srv.port),
                                        ("127.0.0.1", 0)], 1,
                                  heartbeat_s=0.3, stall_timeout_s=60.0,
                                  require_auth=False,
                                  ledger_backend="python",
                                  bft_keys=vkeys)
            with pytest.raises(RuntimeError, match="certificate"):
                standby._follow((srv.host, srv.port))
            assert standby.ledger.log_size() == 0   # nothing replicated
        finally:
            c.close()
            if standby is not None:
                standby.stop()
            srv.close()


class TestValidatorRejoin:
    """Auth evidence lives only in the original writer's process, so a
    validator that restarts (the crash side of f-tolerance) must be able
    to resync historical CLIENT ops on their quorum certificates alone —
    and on nothing less."""

    def test_certified_backlog_admitted_without_auth(self):
        wallets, _ = provision_wallets(CFG.client_num, b"bft-rejoin-01")
        vwallets, vkeys = provision_validators(N_VALIDATORS,
                                               b"bft-rejoin-01")
        nodes = [ValidatorNode(CFG, w, i, validator_keys=vkeys)
                 for i, w in enumerate(vwallets)]
        for v in nodes:
            v.start()
        try:
            # certify op 0 through validators 0-2 only (exactly quorum);
            # validator 3 plays the crashed-then-restarted replica
            asm = CertificateAssembler(
                [(v.host, v.port) for v in nodes[:3]], vkeys, QUORUM,
                timeout_s=5.0)
            w = wallets[0]
            led = make_ledger(CFG, backend="python")
            led.register_node(w.address)
            op = led.log_op(0)
            auth = {"tag": _sign(w, "register", 0, b""),
                    "pubkey": w.public_bytes.hex()}
            cert = asm.certify(0, op, auth, b"\0" * 32)
            asm.close()
            assert cert is not None

            vc = ValidatorClient((nodes[3].host, nodes[3].port),
                                 timeout_s=5.0)
            # no auth, no cert: refused (a bare writer claim is nothing)
            r = vc.request("bft_validate", i=0, op=op.hex(), auth=None)
            assert not r.get("ok") and r.get("status") == "AUTH", r
            # a certificate for a DIFFERENT op admits nothing
            other = make_ledger(CFG, backend="python")
            other.register_node(wallets[1].address)
            r = vc.request("bft_validate", i=0, op=other.log_op(0).hex(),
                           auth=None, cert=cert.to_wire())
            assert not r.get("ok"), r
            # the real certificate admits the op without auth — and the
            # pubkey rides along so the rejoined directory stays complete
            r = vc.request("bft_validate", i=0, op=op.hex(),
                           auth={"pubkey": w.public_bytes.hex()},
                           cert=cert.to_wire())
            assert r.get("ok"), r
            assert nodes[3].ledger.log_size() == 1
            assert nodes[3].directory.knows(w.address)
            # and its vote verifies like any other
            from bflc_demo_tpu.comm.bft import cert_payload
            from bflc_demo_tpu.comm.identity import verify_signature
            assert verify_signature(
                vkeys[3], cert_payload(0, b"\0" * 32, op,
                                       next_head(b"\0" * 32, op)),
                bytes.fromhex(r["sig"]))
            vc.close()
        finally:
            for v in nodes:
                v.close()


class TestBatchedCertification:
    """PR 3: `bft_vote_batch` / `certify_range` — one round-trip per
    validator for a contiguous op range.  The certificates must be
    byte-compatible with the single-op path (same payload layout,
    position-bound, chain-linked, accepted by the unchanged
    `verify_certificate`), idempotent re-asks must re-sign, a lagging
    replica must catch up on certified backlog, and a conflicting
    replica must stop the fast path cold so the evidence-carrying
    single-op machinery takes over."""

    def _signed_register_ops(self, wallets):
        led = make_ledger(CFG, backend="python")
        entries = []
        for w in wallets:
            led.register_node(w.address)
            entries.append((led.log_op(led.log_size() - 1),
                            {"tag": _sign(w, "register", 0, b""),
                             "pubkey": w.public_bytes.hex()}))
        return entries

    def test_range_certifies_and_verifies_like_single_path(self):
        wallets, _ = provision_wallets(CFG.client_num, b"bft-batch-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-batch-01")
        try:
            entries = self._signed_register_ops(wallets[:4])
            asm = CertificateAssembler(eps, vkeys, QUORUM, timeout_s=5.0)
            certs = asm.certify_range(0, entries, b"\0" * 32)
            assert all(c is not None for c in certs)
            prev = b"\0" * 32
            for i, ((op, _), cert) in enumerate(zip(entries, certs)):
                # the unchanged verifier accepts every batch certificate
                assert verify_certificate(
                    cert, index=i, prev_head=prev, op=op, quorum=QUORUM,
                    validator_keys=vkeys), i
                assert len(cert.sigs) == N_VALIDATORS
                prev = next_head(prev, op)
            # idempotent re-ask (a writer retrying after a lost reply):
            # every validator re-signs the ops it already holds
            certs2 = asm.certify_range(0, entries, b"\0" * 32)
            assert all(c is not None for c in certs2)
            # and the single-op path interoperates on the same replicas
            c0 = asm.certify(0, entries[0][0], entries[0][1], b"\0" * 32)
            assert c0 is not None and c0.op_hash == certs[0].op_hash
            asm.close()
        finally:
            for v in nodes:
                v.close()

    def test_lagging_validator_catches_up_inside_batch(self):
        """A validator that missed certified history (crash+rejoin) is
        replayed the backlog — certificates riding along in place of the
        writer-process-local auth evidence — within the batch call."""
        wallets, _ = provision_wallets(CFG.client_num, b"bft-batch-02")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-batch-02")
        try:
            entries = self._signed_register_ops(wallets[:4])
            # certify ops 0-1 through validators 0-2 only: validator 3
            # stays two ops behind
            asm3 = CertificateAssembler(eps[:3], vkeys, QUORUM,
                                        timeout_s=5.0)
            backlog = {}
            prev = b"\0" * 32
            for i in range(2):
                op, auth = entries[i]
                cert = asm3.certify(i, op, auth, prev)
                assert cert is not None
                backlog[i] = (op, auth, cert.to_wire())
                prev = next_head(prev, op)
            asm3.close()
            # now batch-certify ops 2-3 through ALL validators; the
            # assembler must catch validator 3 up from the backlog
            asm = CertificateAssembler(
                eps, vkeys, QUORUM, timeout_s=5.0,
                backlog_fn=lambda j: backlog[j])
            certs = asm.certify_range(2, entries[2:], prev)
            assert all(c is not None for c in certs)
            # full 4-sig certificates prove validator 3 really voted
            assert all(len(c.sigs) == N_VALIDATORS for c in certs)
            assert nodes[3].ledger.log_size() == 4
            asm.close()
        finally:
            for v in nodes:
                v.close()

    def test_conflicting_replica_stops_fast_path_not_safety(self):
        """A validator already bound to a DIFFERENT op at the tip makes
        the batch fast path stop at that position (no certificate from
        the remaining thin quorum is assembled with fewer than quorum
        sigs) — never a forced vote: moving a bound replica takes the
        single-op path's quorum evidence."""
        wallets, _ = provision_wallets(CFG.client_num, b"bft-batch-03")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-batch-03")
        try:
            entries = self._signed_register_ops(wallets[:3])
            # poison validator 0 with a different op at position 0 via a
            # direct single vote (auth is valid — it is a real client op,
            # just a DIFFERENT one)
            other = self._signed_register_ops([wallets[3]])[0]
            vc = ValidatorClient(eps[0], timeout_s=5.0)
            r = vc.request("bft_validate", i=0, op=other[0].hex(),
                           auth=other[1])
            assert r.get("ok"), r
            vc.close()
            asm = CertificateAssembler(eps, vkeys, QUORUM, timeout_s=5.0)
            certs = asm.certify_range(0, entries, b"\0" * 32)
            # quorum still reachable (3 clean validators) for pos 0; the
            # conflicted validator contributed nothing there
            if certs[0] is not None:
                assert 0 not in certs[0].sigs
                assert len(certs[0].sigs) >= QUORUM
            # and every certificate that did come out verifies
            prev = b"\0" * 32
            for i, ((op, _), cert) in enumerate(zip(entries, certs)):
                if cert is None:
                    break
                assert verify_certificate(
                    cert, index=i, prev_head=prev, op=op, quorum=QUORUM,
                    validator_keys=vkeys)
                prev = next_head(prev, op)
            asm.close()
        finally:
            for v in nodes:
                v.close()

    def test_server_drains_backlog_batched(self):
        """LedgerServer._ensure_certified drains the whole uncertified
        backlog per call: a burst of mutations certifies in one
        round-trip window, every op-stream certificate verifies, and
        `certified_size` catches the log tip."""
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"bft-batch-04")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-batch-04")
        server = LedgerServer(CFG, _init_blob(),
                              bft_validators=eps, bft_keys=vkeys)
        server.start()
        try:
            from bflc_demo_tpu.comm.ledger_service import \
                CoordinatorClient
            c = CoordinatorClient(server.host, server.port)
            _register_all(c, wallets)
            _drive_round(c, wallets, 0)
            info = c.request("info")
            assert info["epoch"] == 1
            assert info["certified_size"] == info["log_size"]
            # every certificate in the mirror chain-verifies
            prev = b"\0" * 32
            for i in range(info["log_size"]):
                op = server.ledger.log_op(i)
                cert = CommitCertificate.from_wire(server._certs[i])
                assert verify_certificate(
                    cert, index=i, prev_head=prev, op=op, quorum=QUORUM,
                    validator_keys=vkeys), i
                prev = next_head(prev, op)
            c.close()
        finally:
            server.close()
            for v in nodes:
                v.close()

    def test_legacy_sequential_mode_still_green(self):
        """BFLC_CONTROL_PLANE_LEGACY pins _cert_batch to 1 (the pre-PR
        one-op-per-round-trip path) — the benchmark baseline must remain
        a working configuration, not a strawman."""
        wallets, _ = provision_wallets(CFG.client_num, b"bft-batch-05")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-batch-05")
        server = LedgerServer(CFG, _init_blob(),
                              bft_validators=eps, bft_keys=vkeys)
        server._cert_batch = 1          # what the legacy env pins
        server.start()
        try:
            from bflc_demo_tpu.comm.ledger_service import \
                CoordinatorClient
            c = CoordinatorClient(server.host, server.port)
            _register_all(c, wallets)
            _drive_round(c, wallets, 0)
            info = c.request("info")
            assert info["epoch"] == 1
            assert info["certified_size"] == info["log_size"]
            c.close()
        finally:
            server.close()
            for v in nodes:
                v.close()


class TestBFTFailover:
    """Fail-stop and Byzantine layers compose: the writer dies, the
    standby promotes over the certified chain — certifying its own fence
    op with the same validator quorum — and certificate-checking clients
    finish the next round against it."""

    def test_promotion_certifies_and_round_continues(self):
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"bft-failover-01")
        sb_wallet = Wallet.from_seed(b"bft-failover-sb-1")
        skeys = {1: sb_wallet.public_bytes}
        nodes, eps, vkeys = _mk_validators(seed=b"bft-failover-01")
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python",
                           standby_keys=skeys,
                           bft_validators=eps, bft_keys=vkeys,
                           bft_timeout_s=8.0)
        srv.start()
        standby = Standby(CFG, [(srv.host, srv.port), ("127.0.0.1", 0)], 1,
                          heartbeat_s=0.3, stall_timeout_s=60.0,
                          ledger_backend="python", wallet=sb_wallet,
                          standby_keys=skeys,
                          bft_validators=eps, bft_keys=vkeys,
                          bft_timeout_s=8.0)
        standby.endpoints[1] = (standby.host, standby.port)
        threading.Thread(target=standby.run, daemon=True).start()
        client = FailoverClient([(srv.host, srv.port),
                                 (standby.host, standby.port)],
                                timeout_s=20.0, standby_keys=skeys,
                                bft_keys=vkeys)
        try:
            _register_all(client, wallets)
            _drive_round(client, wallets, epoch=0)
            info = client.request("info")
            assert info["epoch"] == 1
            size_before = info["log_size"]
            deadline = time.monotonic() + 20
            while standby.ledger.log_size() < size_before:
                assert time.monotonic() < deadline, "standby lagging"
                time.sleep(0.05)
            # every replicated op arrived certified
            assert len(standby._certs) >= size_before

            srv.close()
            assert standby.promoted.wait(timeout=30), "no promotion"
            # the dying writer's open connection may answer one last
            # request — rotate until the PROMOTED generation replies
            client.close()
            deadline = time.monotonic() + 20
            while True:
                info2 = client.request("info")
                if info2["gen"] == 1:
                    break
                assert time.monotonic() < deadline, info2
                client.close()
                time.sleep(0.1)
            assert info2["epoch"] == 1
            # the promote fence op itself is certified
            assert info2["certified_size"] == info2["log_size"] \
                == size_before + 1
            # the promoted chain extends the certified history on the
            # validators too
            for v in nodes:
                assert v.ledger.generation == 1
            _drive_round(client, wallets, epoch=1)
            info3 = client.request("info")
            assert info3["epoch"] == 2
            assert info3["certified_size"] == info3["log_size"]
        finally:
            client.close()
            standby.stop()
            srv.close()
            for v in nodes:
                v.close()


class TestLivenessRepair:
    """Round 7: certification recovers from replica divergence instead of
    stalling forever (resync-and-retry + abandon/re-proposal, comm.bft).
    Safety stays intact: exactly one op ever certifies per position."""

    def _two_valid_ops(self, wallets):
        forks = []
        for w in wallets[:2]:
            led = make_ledger(CFG, backend="python")
            led.register_node(w.address)
            forks.append((led.log_op(0),
                          {"tag": _sign(w, "register", 0, b""),
                           "pubkey": w.public_bytes.hex()}))
        return forks

    def test_equivocating_writer_stalls_then_repair_certifies(self):
        """The documented round-6 stall: an equivocating writer diverges
        the validators 2-2 at one position — no branch can quorum.  A
        subsequent honest proposal now drives the abandon round, the
        mandate rule picks the one safely bindable op, diverged
        validators roll back and re-vote, and certification RECOVERS —
        including for the next fresh op."""
        wallets, _ = provision_wallets(CFG.client_num, b"bft-live-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-live-01")
        try:
            (opx, authx), (opy, authy) = self._two_valid_ops(wallets)
            # the equivocation: X to validators {0,1}, Y to {2,3}
            for op, auth, half in ((opx, authx, eps[:2]),
                                   (opy, authy, eps[2:])):
                asm = CertificateAssembler(half, vkeys, 1, timeout_s=5.0)
                assert asm.certify(0, op, auth, b"\0" * 32) is not None
                asm.close()
            # pre-repair this stalled permanently (comm.bft round-6 doc);
            # now the honest re-proposal repairs.  The mandate may pick
            # either branch (2-2 ties are free-choice; a 3-statement
            # proof can mandate the other side) — what matters is that
            # EXACTLY ONE certifies and everyone converges.
            asm = CertificateAssembler(eps, vkeys, QUORUM, timeout_s=5.0)
            cert = asm.certify(0, opx, authx, b"\0" * 32)
            winner, wauth = opx, authx
            if cert is None:
                assert asm.superseded_op == opy, \
                    "no certificate and no mandate: still stalled"
                winner, wauth = opy, authy
                cert = asm.certify(0, opy, authy, b"\0" * 32)
            asm.close()
            assert cert is not None, "repair failed to certify any op"
            assert cert.attempt >= 1       # it took a repair round
            assert verify_certificate(cert, index=0, prev_head=b"\0" * 32,
                                      op=winner, quorum=QUORUM,
                                      validator_keys=vkeys)
            for v in nodes:                # full convergence, no fork
                assert v.ledger.log_size() == 1
                assert v.ledger.log_op(0) == winner
                assert sorted(v._voted) == [0]
            # the LOSER op can never certify now: every valid repair
            # proof reports the winner with a unique f+1 mandate
            loser, lauth = (opy, authy) if winner is opx else (opx, authx)
            asm = CertificateAssembler(eps, vkeys, QUORUM, timeout_s=5.0)
            assert asm.certify(0, loser, lauth, b"\0" * 32) is None
            assert asm.superseded_op == winner
            asm.close()
            # and the chain continues: the next FRESH op certifies clean
            w2 = next(w for w in wallets
                      if w.address not in (wallets[0].address,
                                           wallets[1].address))
            led = make_ledger(CFG, backend="python")
            assert led.apply_op(winner) == LedgerStatus.OK
            led.register_node(w2.address)
            op2 = led.log_op(1)
            asm = CertificateAssembler(eps, vkeys, QUORUM, timeout_s=5.0)
            cert2 = asm.certify(1, op2,
                                {"tag": _sign(w2, "register", 0, b""),
                                 "pubkey": w2.public_bytes.hex()},
                                next_head(b"\0" * 32, winner))
            asm.close()
            assert cert2 is not None
        finally:
            for v in nodes:
                v.close()

    def test_partitioned_validator_heals_and_rejoins(self):
        """A validator partitioned mid-certification misses ops; on heal
        the certified backlog carries it forward — one vote per position,
        no double-voting, full head agreement."""
        wallets, _ = provision_wallets(CFG.client_num, b"bft-heal-01")
        vwallets, vkeys = provision_validators(N_VALIDATORS, b"bft-heal-01")
        nodes = [ValidatorNode(CFG, w, i, validator_keys=vkeys)
                 for i, w in enumerate(vwallets)]
        for v in nodes:
            v.start()
        try:
            chain = make_ledger(CFG, backend="python")
            certs, auths = {}, {}

            def backlog(j):
                return chain.log_op(j), auths.get(j), certs.get(j)

            # ops 0..2 certify while validator 3 is partitioned away
            asm = CertificateAssembler([(v.host, v.port)
                                        for v in nodes[:3]],
                                       vkeys, QUORUM, timeout_s=5.0,
                                       backlog_fn=backlog)
            for j, w in enumerate(wallets[:3]):
                prev = chain.log_head() if chain.log_size() else b"\0" * 32
                chain.register_node(w.address)
                auths[j] = {"tag": _sign(w, "register", 0, b""),
                            "pubkey": w.public_bytes.hex()}
                cert = asm.certify(j, chain.log_op(j), auths[j], prev)
                assert cert is not None
                certs[j] = cert.to_wire()
            asm.close()
            assert nodes[3].ledger.log_size() == 0
            # heal: the next certification resyncs validator 3 from the
            # certified backlog and its vote joins the certificate
            w3 = wallets[3]
            prev = chain.log_head()
            chain.register_node(w3.address)
            auths[3] = {"tag": _sign(w3, "register", 0, b""),
                        "pubkey": w3.public_bytes.hex()}
            asm = CertificateAssembler([(v.host, v.port) for v in nodes],
                                       vkeys, QUORUM, timeout_s=5.0,
                                       backlog_fn=backlog)
            cert = asm.certify(3, chain.log_op(3), auths[3], prev)
            asm.close()
            assert cert is not None
            assert len(cert.sigs) == N_VALIDATORS    # the healed one too
            for v in nodes:
                assert v.ledger.log_size() == 4
                assert v.ledger.log_head() == chain.log_head()
                assert sorted(v._voted) == [0, 1, 2, 3]   # exactly once
        finally:
            for v in nodes:
                v.close()

    def test_stale_fork_validator_resynced_by_certificate(self):
        """A validator that bound a stranded op keeps voting on its own
        fork (valid-looking replies, wrong head).  The assembler detects
        the bad-head vote and heals it by presenting the commit
        certificate for the canonical op — rollback, rejoin, re-vote."""
        wallets, _ = provision_wallets(CFG.client_num, b"bft-fork-heal-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-fork-heal-01")
        try:
            (opx, authx), (opy, authy) = self._two_valid_ops(wallets)
            # validator 3 binds the STRANDED op Y at position 0 (a dead
            # writer's last proposal that never certified)
            vc = ValidatorClient(eps[3], timeout_s=5.0)
            assert vc.request("bft_validate", i=0, op=opy.hex(),
                              auth=authy)["ok"]
            vc.close()
            chain = make_ledger(CFG, backend="python")
            certs, auths = {}, {0: authx}

            def backlog(j):
                return chain.log_op(j), auths.get(j), certs.get(j)

            asm = CertificateAssembler(eps, vkeys, QUORUM, timeout_s=5.0,
                                       backlog_fn=backlog)
            # X certifies through validators 0-2 (v3 answers CONFLICT or
            # a stale-fork vote; the quorum does not need it)
            assert chain.apply_op(opx) == LedgerStatus.OK
            cert0 = asm.certify(0, opx, authx, b"\0" * 32)
            assert cert0 is not None
            certs[0] = cert0.to_wire()
            # the chain moves on; v3 extends its private fork until the
            # assembler heals it with cert0 — the next certificate must
            # end up carrying ALL FOUR signatures
            w2 = wallets[2]
            chain.register_node(w2.address)
            auths[1] = {"tag": _sign(w2, "register", 0, b""),
                        "pubkey": w2.public_bytes.hex()}
            cert1 = asm.certify(1, chain.log_op(1), auths[1],
                                next_head(b"\0" * 32, opx))
            asm.close()
            assert cert1 is not None
            assert len(cert1.sigs) == N_VALIDATORS, \
                "stale-fork validator was not healed"
            assert nodes[3].ledger.log_op(0) == opx
            assert nodes[3].ledger.log_head() == chain.log_head()
            assert sorted(nodes[3]._voted) == [0, 1]
        finally:
            for v in nodes:
                v.close()


class TestBacklogResyncThroughDivergence:
    """The 100-round-soak wedge (round 7): a validator that voted a
    LOSING op while lagging holds a diverged suffix; later backlog
    replay of the canonical chain mis-applies onto its fork (here: the
    same register op landing DUPLICATE) and, pre-fix, refused forever —
    the replica could never rejoin.  The backlog path must escalate a
    replay refusal to certificate resync at the true divergence point."""

    def test_backlog_refusal_triggers_cert_resync(self):
        wallets, _ = provision_wallets(CFG.client_num, b"bft-wedge-01")
        nodes, eps, vkeys = _mk_validators(seed=b"bft-wedge-01")
        try:
            regs = []
            for w in wallets[:5]:
                led = make_ledger(CFG, backend="python")
                led.register_node(w.address)
                regs.append((led.log_op(0),
                             {"tag": _sign(w, "register", 0, b""),
                              "pubkey": w.public_bytes.hex()}))
            # canonical chain: A, B, E, F (E = the op validator 3 will
            # have stranded at position 1 — the client's retry landed it
            # at position 2 of the canonical chain)
            (opa, aa), (opb, ab), (ope, ae), (opf, af), (opg, ag) = regs
            chain = make_ledger(CFG, backend="python")
            order = [(opa, aa), (opb, ab), (ope, ae), (opf, af)]
            certs, auths = {}, {}

            def backlog(j):
                return chain.log_op(j), auths.get(j), certs.get(j)

            # validator 3 sees op A, then strands op E at position 1
            vc = ValidatorClient(eps[3], timeout_s=5.0)
            assert vc.request("bft_validate", i=0, op=opa.hex(),
                              auth=aa)["ok"]
            assert vc.request("bft_validate", i=1, op=ope.hex(),
                              auth=ae)["ok"]
            vc.close()
            # the canonical chain certifies through validators 0-2
            asm3 = CertificateAssembler(eps[:3], vkeys, QUORUM,
                                        timeout_s=5.0,
                                        backlog_fn=backlog)
            for j, (op, auth) in enumerate(order):
                prev = chain.log_head() if chain.log_size() else b"\0" * 32
                assert chain.apply_op(op) == LedgerStatus.OK
                auths[j] = auth
                cert = asm3.certify(j, op, auth, prev)
                assert cert is not None, f"op {j} failed to certify"
                certs[j] = cert.to_wire()
            asm3.close()
            assert nodes[3].ledger.log_size() == 2      # stranded fork
            # full-fleet certification of the next op: validator 3 is
            # BEHIND (OUT_OF_ORDER) and its fork makes canonical op 2
            # (register E) refuse as ALREADY_REGISTERED mid-backlog —
            # the resync escalation must heal it at position 1
            prev = chain.log_head()
            assert chain.apply_op(opg) == LedgerStatus.OK
            auths[4] = ag
            asm = CertificateAssembler(eps, vkeys, QUORUM, timeout_s=5.0,
                                       backlog_fn=backlog)
            cert = asm.certify(4, opg, ag, prev)
            asm.close()
            assert cert is not None
            assert len(cert.sigs) == N_VALIDATORS, \
                "wedged validator did not rejoin through the backlog"
            assert nodes[3].ledger.log_size() == 5
            assert nodes[3].ledger.log_head() == chain.log_head()
            assert nodes[3].ledger.log_op(1) == opb     # fork healed
        finally:
            for v in nodes:
                v.close()


def _rollback_clone(led, upto):
    """Fresh ledger replaying ops [0, upto) of `led` — drops the suffix a
    hostile writer applied locally but failed to certify."""
    clone = make_ledger(CFG, backend="python")
    for i in range(upto):
        assert clone.apply_op(led.log_op(i)) == LedgerStatus.OK
    return clone
