"""On-mesh batched aggregation engine (bflc_demo_tpu/meshagg; ISSUE 11).

The hard property under test is DIFFERENTIAL DETERMINISM: the compiled
mesh leg and the pre-engine host loop must produce byte-identical
certified bytes (REDUCTION SPEC v1), pinned three ways —

- golden digests captured from the PRE-ENGINE tree for the writer
  merge and the hier cell partial (`BFLC_MESH_AGG_LEGACY=1` must stay
  byte-identical to pre-PR forever);
- golden COMMITTED MODEL HASHES from scripted end-to-end rounds
  through a real LedgerServer (config-1-shaped sync round AND an async
  FedBuff drain with a staleness mix), re-run under both legs;
- the randomized differential checker (tools/check_reduction_spec.py)
  invoked in-process.
"""

import hashlib
import os
import struct
import sys

import numpy as np
import pytest

from bflc_demo_tpu.meshagg import spec
from bflc_demo_tpu.meshagg.engine import (ENGINE, flatten_delta,
                                          score_candidates_batched)
from bflc_demo_tpu.utils.serialization import pack_entries, pack_pytree

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

# digests captured from the pre-meshagg tree (ISSUE 11): any drift in
# the certified aggregation arithmetic — either leg — fails here
GOLDEN_AGG = ("df85ae5b7b16077404d72e33805da33a"
              "0d0f97509c3fdcdc91e55ed5e5747ee1")
GOLDEN_CELL = ("3c8d67f4d02d436e58390d8a065c1f13"
               "283a13b7e5dfdd5d629a7c56e3b24c53")
GOLDEN_SYNC_MODEL = ("cc8d5f5257a2dc49be71fe88ce91f039"
                     "a8779af406cd58ba187933a731bf463f")
GOLDEN_ASYNC_MODEL = ("9b459d464fb79f6e189c9939f08c8704"
                      "52ce805ceb909325d9c76c271e39733b")


def _golden_scenario():
    rng = np.random.default_rng(20260804)
    keys = ["/W1", "/b1", "/W2", "/b2"]
    shapes = {"/W1": (16, 8), "/b1": (8,), "/W2": (8, 3), "/b2": (3,)}
    g = {k: rng.standard_normal(shapes[k]).astype(np.float32)
         for k in keys}
    deltas = [{k: rng.standard_normal(shapes[k]).astype(np.float32)
               for k in keys} for _ in range(12)]
    weights = [float(10 + i) * (1.0 / np.sqrt(1.0 + (i % 4)))
               for i in range(12)]
    selected = [0, 2, 3, 5, 7, 8, 10]
    return rng, keys, shapes, g, deltas, weights, selected


class TestGoldenPins:
    def test_host_leg_pins_pre_pr_merge_bytes(self, monkeypatch):
        monkeypatch.setenv("BFLC_MESH_AGG_LEGACY", "1")
        _, _, _, g, deltas, weights, selected = _golden_scenario()
        out = ENGINE.aggregate_flat(g, deltas, weights, selected, 0.05)
        assert hashlib.sha256(
            pack_entries(out)).hexdigest() == GOLDEN_AGG

    def test_mesh_leg_reproduces_pre_pr_merge_bytes(self):
        _, _, _, g, deltas, weights, selected = _golden_scenario()
        out = ENGINE.aggregate_flat(g, deltas, weights, selected, 0.05,
                                    force_leg="mesh")
        assert hashlib.sha256(
            pack_entries(out)).hexdigest() == GOLDEN_AGG

    def test_staged_rows_leg_reproduces_pre_pr_merge_bytes(self):
        # the writer's actual mesh path: rows staged at admission,
        # merged via aggregate_rows
        _, keys, _, g, deltas, weights, selected = _golden_scenario()
        rows = [flatten_delta(d, sorted(keys)) for d in deltas]
        out = ENGINE.aggregate_rows(g, rows, weights, selected, 0.05,
                                    force_leg="mesh")
        assert hashlib.sha256(
            pack_entries(out)).hexdigest() == GOLDEN_AGG
        # and the rows-based HOST fallback (unflatten) is identical too
        out_h = ENGINE.aggregate_rows(g, rows, weights, selected, 0.05,
                                      force_leg="host")
        assert hashlib.sha256(
            pack_entries(out_h)).hexdigest() == GOLDEN_AGG

    def test_cell_partial_bytes_unchanged(self):
        from bflc_demo_tpu.hier.partial import cell_partial
        rng, keys, shapes, _, _, _, _ = _golden_scenario()
        # consume the same rng stream the capture script used
        admitted = []
        for i in range(7):
            flat = {k: rng.standard_normal(shapes[k]).astype(np.float32)
                    for k in keys}
            admitted.append((f"0x{i:040x}", flat, 10 + 3 * i,
                             0.5 + 0.1 * i))
        partial, n, cost = cell_partial(admitted)
        assert hashlib.sha256(
            pack_entries(partial)).hexdigest() == GOLDEN_CELL
        assert n == 7 and cost == pytest.approx(0.800000011920929)


class TestEnginePolicy:
    def test_legacy_env_pins_host_leg(self, monkeypatch):
        monkeypatch.setenv("BFLC_MESH_AGG_LEGACY", "1")
        assert ENGINE.choose_leg(10_000) == "legacy"

    def test_min_batch_threshold(self, monkeypatch):
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "8")
        assert ENGINE.choose_leg(7) == "host"
        # >= threshold: mesh iff the self-check passes on this platform
        assert ENGINE.choose_leg(8) == (
            "mesh" if ENGINE._mesh_ready() else "host")

    def test_selfcheck_passes_on_this_platform(self):
        # the one-time no-FMA differential self-check must hold here —
        # if this fails, the toolchain contracts the spec's mul/add and
        # the engine (correctly) refuses the compiled leg
        assert ENGINE._mesh_ready()
        assert ENGINE.report()["selfcheck"] == "ok"

    def test_program_cache_reuse(self):
        before = ENGINE.compile_total
        rng = np.random.default_rng(3)
        deltas = [{"/x": rng.standard_normal((6, 5)).astype(np.float32)}
                  for _ in range(21)]
        w = spec.merge_weight_vector([1.0] * 21, list(range(21)), 21)
        ENGINE.weighted_sum(["/x"], deltas, w, float(w.sum()),
                            force_leg="mesh")
        ENGINE.weighted_sum(["/x"], deltas, w, float(w.sum()),
                            force_leg="mesh")
        # same (N, P) geometry = same compiled program; and a same-size
        # DIFFERENT tree structure shares it too (the kernel is flat)
        deltas2 = [{"/a": rng.standard_normal((3, 5)).astype(np.float32),
                    "/b": rng.standard_normal((15,)).astype(np.float32)}
                   for _ in range(21)]
        ENGINE.weighted_sum(["/a", "/b"], deltas2, w, float(w.sum()),
                            force_leg="mesh")
        assert ENGINE.compile_total <= before + 1


class TestDifferentialChecker:
    def test_randomized_host_vs_mesh_exact(self):
        from check_reduction_spec import run_differential
        out = run_differential(trials=8, seed=20260804, max_n=48)
        assert out["mismatches"] == [], out["mismatches"]

    def test_randomized_writer_vs_rederive_exact(self):
        """The rederive leg (ISSUE 15): randomized trees/weights/
        selections x dtype x density produce byte-identical committed
        model hashes via the writer path and the validator
        re-derivation path (bflc_demo_tpu.rederive), with every shard
        leaf equal and the shard union covering the model."""
        from check_reduction_spec import run_rederive_differential
        out = run_rederive_differential(trials=6, seed=20260804,
                                        max_n=16)
        assert out["mismatches"] == [], out["mismatches"]

    def test_sparse_decode_images_host_vs_mesh_exact(self):
        """Sparse and sparse x i8/f16 decode images (ISSUE 13) reduce
        byte-identically on both legs — forced coverage of every
        (dtype, density) cell the randomized stream samples."""
        from check_reduction_spec import _random_flat
        rng = np.random.default_rng(20260804)
        shapes = {"/W": (24, 16), "/b": (16,)}
        keys = sorted(shapes)
        for quant in ("f32", "f16", "i8"):
            for density in (0.1, 0.01):
                deltas = [_random_flat(rng, shapes, quant, density)
                          for _ in range(20)]
                w = spec.merge_weight_vector(
                    [float(10 + i) for i in range(20)],
                    list(range(20)), 20)
                wsum = max(float(w.sum()), 1e-12)
                with np.errstate(over="ignore", invalid="ignore"):
                    host = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                               force_leg="host")
                    mesh = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                               force_leg="mesh")
                for k in keys:
                    assert np.asarray(host[k]).tobytes() == \
                        np.asarray(mesh[k]).tobytes(), (quant, density,
                                                        k)


def _sign(w, kind, epoch, payload):
    from bflc_demo_tpu.comm.identity import _op_bytes
    return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()


def _tree(rng, scale=1.0):
    return {"W1": (rng.standard_normal((16, 8)) * scale
                   ).astype(np.float32),
            "b1": (rng.standard_normal((8,)) * scale
                   ).astype(np.float32),
            "W2": (rng.standard_normal((8, 3)) * scale
                   ).astype(np.float32)}


def _sync_round_model_hash(**cfg_overrides):
    """Scripted config-1 sync round through a real LedgerServer; the
    committed model hash is the certified artifact under test.
    `cfg_overrides` lets byte-invariance pins (e.g. REDUCTION SPEC v2
    `reduce_blocks`, tests/test_blocked.py) re-run the identical script
    under a different genome."""
    from bflc_demo_tpu.comm.identity import provision_wallets
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    from bflc_demo_tpu.protocol.constants import ProtocolConfig
    cfg = ProtocolConfig(client_num=20, comm_count=4, aggregate_count=6,
                         needed_update_count=10, learning_rate=0.05,
                         batch_size=16, **cfg_overrides).validate()
    rng = np.random.default_rng(11)
    blob0 = pack_pytree(_tree(rng))
    wallets, _ = provision_wallets(20, b"meshagg-parity-seed")
    srv = LedgerServer(cfg, blob0)
    srv.start()
    cl = CoordinatorClient(srv.host, srv.port)
    try:
        for w in wallets:
            assert cl.request("register", addr=w.address,
                              pubkey=w.public_bytes.hex(),
                              tag=_sign(w, "register", 0, b""))["ok"]
        committee = set(cl.request("committee")["committee"])
        trainers = [w for w in wallets if w.address not in committee]
        for i, w in enumerate(trainers[:10]):
            blob = pack_pytree(_tree(np.random.default_rng(100 + i),
                                     0.1))
            d = hashlib.sha256(blob).digest()
            payload = d + struct.pack("<qd", 20 + i, 1.0 + 0.05 * i)
            r = cl.request("upload", addr=w.address, blob=blob,
                           hash=d.hex(), n=20 + i,
                           cost=1.0 + 0.05 * i, epoch=0,
                           tag=_sign(w, "upload", 0, payload))
            assert r["ok"], r
        for j, w in enumerate([w for w in wallets
                               if w.address in committee]):
            row = [0.5 + 0.01 * (j + u) for u in range(10)]
            payload = struct.pack("<10d", *row)
            r = cl.request("scores", addr=w.address, epoch=0,
                           scores=row,
                           tag=_sign(w, "scores", 0, payload))
            assert r["ok"] or r.get("status") == "WRONG_EPOCH", r
        assert cl.request("info")["epoch"] == 1
        return cl.request("model")["hash"]
    finally:
        cl.close()
        srv.close()


def _async_drain_model_hash(**cfg_overrides):
    """Two scripted FedBuff drains (the second with a staleness mix)
    through a real async-mode LedgerServer.  `cfg_overrides` as in
    `_sync_round_model_hash`."""
    from bflc_demo_tpu.comm.identity import _op_bytes, provision_wallets
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    from bflc_demo_tpu.ledger.base import ascores_sign_payload
    from bflc_demo_tpu.protocol.constants import ProtocolConfig
    cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                         needed_update_count=4, learning_rate=0.05,
                         batch_size=16, async_buffer=4,
                         max_staleness=4, **cfg_overrides).validate()
    rng = np.random.default_rng(12)
    blob0 = pack_pytree(_tree(rng))
    wallets, _ = provision_wallets(8, b"meshagg-async-parity")
    srv = LedgerServer(cfg, blob0)
    srv.start()
    cl = CoordinatorClient(srv.host, srv.port)
    try:
        for w in wallets:
            assert cl.request("register", addr=w.address,
                              pubkey=w.public_bytes.hex(),
                              tag=_sign(w, "register", 0, b""))["ok"]
        committee = set(cl.request("committee")["committee"])
        trainers = [w for w in wallets if w.address not in committee]
        comm_ws = [w for w in wallets if w.address in committee]

        def aupload(i, w, base):
            blob = pack_pytree(_tree(np.random.default_rng(200 + i),
                                     0.1))
            d = hashlib.sha256(blob).digest()
            payload = d + struct.pack("<qd", 10 + i, 1.0)
            return cl.request("aupload", addr=w.address, blob=blob,
                              hash=d.hex(), n=10 + i, cost=1.0,
                              base_epoch=base,
                              tag=_sign(w, "aupload", base, payload))

        for i, w in enumerate(trainers[:3]):
            assert aupload(i, w, 0)["ok"]
        au = cl.request("aupdates")
        pairs = [(u["aseq"], 0.5 + 0.1 * u["aseq"])
                 for u in au["updates"]]
        w = comm_ws[0]
        assert cl.request(
            "ascores", addr=w.address,
            pairs=[[a, s] for a, s in pairs],
            tag=w.sign(_op_bytes(
                "ascores", w.address, 0,
                ascores_sign_payload(pairs))).hex())["ok"]
        r = aupload(3, trainers[3], 0)
        assert r["ok"] and r["epoch"] == 1, r
        # second drain: two epoch-0 bases (staleness 1) + two fresh
        for i, w in enumerate(trainers[:2]):
            assert aupload(4 + i, w, 0)["ok"]
        for i, w in enumerate(trainers[2:4]):
            assert aupload(6 + i, w, 1)["ok"]
        assert cl.request("info")["epoch"] == 2
        return cl.request("model")["hash"]
    finally:
        cl.close()
        srv.close()


class TestCertifiedHashParity:
    """Acceptance pin: mesh leg and host-loop leg produce IDENTICAL
    certified model hashes at config-1 geometry, sync AND async — and
    both equal the hash the pre-engine tree committed."""

    def test_sync_round_hash_identical_across_legs(self, monkeypatch):
        monkeypatch.setenv("BFLC_MESH_AGG_LEGACY", "1")
        monkeypatch.delenv("BFLC_MESH_AGG_MIN", raising=False)
        legacy = _sync_round_model_hash()
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "1")
        mesh = _sync_round_model_hash()
        assert legacy == mesh == GOLDEN_SYNC_MODEL

    def test_async_drain_hash_identical_across_legs(self, monkeypatch):
        monkeypatch.setenv("BFLC_MESH_AGG_LEGACY", "1")
        monkeypatch.delenv("BFLC_MESH_AGG_MIN", raising=False)
        legacy = _async_drain_model_hash()
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "1")
        mesh = _async_drain_model_hash()
        assert legacy == mesh == GOLDEN_ASYNC_MODEL


class TestBatchedScoring:
    def test_batched_scores_equal_direct_vmap(self):
        import jax.numpy as jnp

        from bflc_demo_tpu.core.scoring import score_candidates
        rng = np.random.default_rng(5)

        def apply_fn(params, x):
            return x @ params["W"] + params["b"]

        g = {"W": jnp.asarray(rng.standard_normal((6, 3))
                              .astype(np.float32)),
             "b": jnp.asarray(rng.standard_normal((3,))
                              .astype(np.float32))}
        deltas = [{"W": jnp.asarray((rng.standard_normal((6, 3)) * 0.1)
                                    .astype(np.float32)),
                   "b": jnp.asarray((rng.standard_normal((3,)) * 0.1)
                                    .astype(np.float32))}
                  for _ in range(5)]
        x = jnp.asarray(rng.standard_normal((32, 6)).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[
            rng.integers(0, 3, size=32)])
        batched = np.asarray(score_candidates_batched(
            apply_fn, g, deltas, 0.05, x, y))
        import jax
        stacked = jax.tree_util.tree_map(
            lambda *t: jnp.stack(t), *deltas)
        direct = np.asarray(score_candidates(apply_fn, g, stacked,
                                             0.05, x, y))
        assert batched.tobytes() == direct.tobytes()


@pytest.mark.slow
class TestMultiDevice:
    """The spec's device-count independence, demonstrated: a forced
    4-device CPU backend must reproduce the single-device bytes (the
    reduction order is protocol, never jax.device_count())."""

    def test_four_device_host_mesh_parity(self):
        import subprocess
        code = (
            "import os, sys\n"
            "sys.path.insert(0, 'tools')\n"
            "from check_reduction_spec import run_differential\n"
            "import jax\n"
            "assert jax.device_count() == 4, jax.devices()\n"
            "out = run_differential(trials=6, seed=1, max_n=32)\n"
            "assert out['mismatches'] == [], out['mismatches']\n"
            "print('MULTIDEV_OK')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                              " --xla_force_host_platform_device_count"
                              "=4"))
        r = subprocess.run([sys.executable, "-c", code],
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))),
                           capture_output=True, text=True, timeout=300,
                           env=env)
        assert r.returncode == 0 and "MULTIDEV_OK" in r.stdout, \
            r.stderr[-2000:]
