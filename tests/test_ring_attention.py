"""Sequence-parallel ring attention + TP sharding tests (8-device CPU mesh).

The invariant: ring/TP execution computes the same function as the
single-device forward with the same parameters.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bflc_demo_tpu.models.transformer import (
    make_transformer_classifier, transformer_forward)
from bflc_demo_tpu.parallel.mesh import make_mesh
from bflc_demo_tpu.parallel.ring_attention import (
    ring_attention, make_sp_transformer_forward, SP_AXIS)
from bflc_demo_tpu.parallel.tp import (make_tp_train_step,
                                       shard_transformer_params)


def _model(seq_len=32):
    return make_transformer_classifier(vocab_size=100, seq_len=seq_len,
                                       num_classes=3, dim=32, depth=2,
                                       heads=4)


def _tokens(rng, b, s, pad_tail=True):
    x = rng.integers(1, 100, (b, s)).astype(np.int32)
    if pad_tail:
        lengths = rng.integers(s // 2, s + 1, b)
        for i in range(b):
            x[i, lengths[i]:] = 0
    return jnp.asarray(x)


class TestRingPallasComposition:
    """Ring x flash-kernel composition: each hop runs the streaming-carry
    Pallas kernel, so block logits never materialise at EITHER level."""

    def _shard_qkv(self, rng, mesh, b=2, s=64, h=2, dh=16):
        def mk():
            return jnp.asarray(rng.standard_normal((b, s, h, dh)),
                               jnp.float32)
        q, k, v = mk(), mk(), mk()
        mask = np.ones((b, s), bool)
        mask[:, 50:] = False
        return q, k, v, jnp.asarray(mask)

    @pytest.mark.parametrize("n_sp", [2, 4])
    def test_pallas_ring_matches_einsum_ring(self, n_sp):
        from bflc_demo_tpu.utils.compat import shard_map
        mesh = make_mesh((n_sp,), (SP_AXIS,))
        rng = np.random.default_rng(13)
        q, k, v, mask = self._shard_qkv(rng, mesh)

        def run(impl):
            def body(q_, k_, v_, m_):
                return ring_attention(q_, k_, v_, m_, SP_AXIS, impl=impl)
            fn = shard_map(body, mesh=mesh,
                           in_specs=(P(None, SP_AXIS), P(None, SP_AXIS),
                                     P(None, SP_AXIS), P(None, SP_AXIS)),
                           out_specs=P(None, SP_AXIS), check_vma=False)
            return jax.jit(fn)(q, k, v, mask)

        got = run("pallas_interpret")
        want = run("einsum")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_pallas_ring_gradients(self):
        """The custom vjp (einsum-ring recompute) produces the einsum
        ring's exact gradients."""
        from bflc_demo_tpu.utils.compat import shard_map
        mesh = make_mesh((2,), (SP_AXIS,))
        rng = np.random.default_rng(14)
        q, k, v, mask = self._shard_qkv(rng, mesh, s=32)

        def loss(impl):
            def body(q_, k_, v_, m_):
                o = ring_attention(q_, k_, v_, m_, SP_AXIS, impl=impl)
                return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2),
                                    SP_AXIS)
            fn = shard_map(body, mesh=mesh,
                           in_specs=(P(None, SP_AXIS), P(None, SP_AXIS),
                                     P(None, SP_AXIS), P(None, SP_AXIS)),
                           out_specs=P(), check_vma=False)
            return lambda q_, k_, v_: jax.jit(fn)(q_, k_, v_, mask) / 2

        gp = jax.grad(loss("pallas_interpret"), argnums=(0, 1, 2))(q, k, v)
        ge = jax.grad(loss("einsum"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, ge):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_sp_forward_with_pallas_ring(self):
        """attention_impl='pallas_interpret' drives the whole sp
        transformer through the composed path; logits match einsum."""
        model = make_transformer_classifier(vocab_size=100, seq_len=32,
                                            num_classes=3, dim=32, depth=1,
                                            heads=2)
        kernel_cfg = make_transformer_classifier(
            vocab_size=100, seq_len=32, num_classes=3, dim=32, depth=1,
            heads=2, attention_impl="pallas_interpret").config
        mesh = make_mesh((4,), (SP_AXIS,))
        rng = np.random.default_rng(15)
        tokens = _tokens(rng, 3, 32)
        params = model.init_params(0)
        want = make_sp_transformer_forward(mesh, model.config)(params,
                                                               tokens)
        got = make_sp_transformer_forward(mesh, kernel_cfg)(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-4, atol=5e-5)

    def test_bad_impl_rejected(self):
        with pytest.raises(ValueError):
            ring_attention(jnp.zeros((1, 8, 1, 8)), jnp.zeros((1, 8, 1, 8)),
                           jnp.zeros((1, 8, 1, 8)), jnp.ones((1, 8), bool),
                           impl="nope")


class TestRingAttention:
    @pytest.mark.parametrize("n_sp", [2, 4, 8])
    def test_matches_single_device(self, n_sp):
        model = _model(seq_len=32)
        cfg = model.config
        mesh = make_mesh((n_sp,), (SP_AXIS,))
        rng = np.random.default_rng(0)
        tokens = _tokens(rng, 4, 32)
        params = model.init_params(0)
        want = transformer_forward(params, tokens, cfg)
        fn = make_sp_transformer_forward(mesh, cfg)
        got = fn(params, tokens)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_heavy_padding(self):
        """Shards that are 100% PAD must not corrupt attention (the
        exp(NEG_INF - NEG_INF) resurrection case)."""
        model = _model(seq_len=32)
        cfg = model.config
        mesh = make_mesh((8,), (SP_AXIS,))
        rng = np.random.default_rng(1)
        tokens = np.array(_tokens(rng, 4, 32, pad_tail=False))
        tokens[:, 6:] = 0       # only the first 6 positions are real
        tokens = jnp.asarray(tokens)
        want = transformer_forward(params := model.init_params(1), tokens,
                                   cfg)
        got = make_sp_transformer_forward(mesh, cfg)(params, tokens)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
        assert np.isfinite(np.asarray(got)).all()

    def test_gradients_flow(self):
        """Ring attention is differentiable (fori_loop of ppermutes)."""
        model = _model(seq_len=16)
        cfg = model.config
        mesh = make_mesh((4,), (SP_AXIS,))
        rng = np.random.default_rng(2)
        tokens = _tokens(rng, 2, 16)
        y = jnp.asarray(np.eye(3, dtype=np.float32)[[0, 1]])
        fn = make_sp_transformer_forward(mesh, cfg)

        def loss(p):
            logits = fn(p, tokens)
            return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), -1))

        g = jax.grad(loss)(model.init_params(2))
        flat = jax.tree_util.tree_leaves(g)
        assert all(np.isfinite(np.asarray(leaf)).all() for leaf in flat)
        assert any(float(jnp.abs(leaf).max()) > 0 for leaf in flat)


class TestTensorParallel:
    def test_tp_train_step_matches_single_device(self):
        model = _model(seq_len=16)
        cfg = model.config
        mesh = make_mesh((2, 4), ("dp", "tp"))
        rng = np.random.default_rng(3)
        tokens = _tokens(rng, 8, 16)
        labels = jnp.asarray(np.eye(3, dtype=np.float32)[
            rng.integers(0, 3, 8)])
        params = model.init_params(3)
        # randomize the zero-init head so BODY gradients are nonzero and
        # the leaf-by-leaf equality below is non-vacuous (GSPMD autodiff
        # is correct by construction, but the test should prove it)
        params["head_w"] = jax.random.normal(
            jax.random.PRNGKey(3), params["head_w"].shape,
            jnp.float32) * 0.5

        # single-device reference step
        def loss_fn(p):
            return jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(
                transformer_forward(p, tokens, cfg)), -1))
        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
        ref_new = jax.tree_util.tree_map(lambda w, g: w - 0.1 * g,
                                         params, ref_grads)

        step = make_tp_train_step(mesh, model.apply, cfg, lr=0.1)
        sharded = shard_transformer_params(params, mesh)
        new_params, loss = step(sharded, tokens, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for ref_leaf, got_leaf in zip(
                jax.tree_util.tree_leaves(ref_new),
                jax.tree_util.tree_leaves(new_params)):
            np.testing.assert_allclose(np.asarray(got_leaf),
                                       np.asarray(ref_leaf),
                                       rtol=2e-4, atol=2e-5)

    def test_params_actually_sharded(self):
        model = _model(seq_len=16)
        mesh = make_mesh((2, 4), ("dp", "tp"))
        sharded = shard_transformer_params(model.init_params(0), mesh)
        wq = sharded["blocks"][0]["wq"]
        assert wq.sharding.spec == P(None, "tp")
        emb = sharded["embed"]
        assert emb.sharding.spec == P("tp", None)


class TestSPTrainStep:
    """Long-context TRAINING: one SGD step with gradients flowing backward
    through the ring must equal the single-device step exactly (up to fp
    reassociation) — including the replicated-vs-sharded gradient split.

    The head MUST be randomized here: the model's zero-init head makes
    every body gradient zero and the equivalence vacuous (the same
    vacuity class as the round-4 long-context post-mortem — an early
    version of this test passed while the body-gradient scaling was
    n_sp x wrong)."""

    def _rand_head(self, params, seed):
        params = dict(params)
        params["head_w"] = jax.random.normal(
            jax.random.PRNGKey(seed), params["head_w"].shape,
            jnp.float32) * 0.5
        params["head_b"] = jnp.asarray(
            np.linspace(-0.2, 0.2, params["head_b"].shape[0]), jnp.float32)
        return params

    def _single_device_step(self, model, params, tokens, labels, lr):
        def loss_fn(p):
            logits = transformer_forward(p, tokens, model.config)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.sum(labels * logp, axis=-1))
        loss, g = jax.value_and_grad(loss_fn)(params)
        new = jax.tree_util.tree_map(
            lambda w, d: w - jnp.asarray(lr, w.dtype) * d.astype(w.dtype),
            params, g)
        return new, loss

    @pytest.mark.parametrize("n_sp", [2, 4])
    def test_matches_single_device_step(self, n_sp):
        from bflc_demo_tpu.parallel.ring_attention import make_sp_train_step
        model = _model(seq_len=32)
        cfg = model.config
        mesh = make_mesh((n_sp,), (SP_AXIS,))
        rng = np.random.default_rng(5)
        tokens = _tokens(rng, 4, 32)
        labels = jnp.asarray(np.eye(cfg.num_classes,
                                    dtype=np.float32)[
            rng.integers(0, cfg.num_classes, 4)])
        params = self._rand_head(model.init_params(5), seed=5)
        want_p, want_l = self._single_device_step(model, params, tokens,
                                                  labels, lr=0.1)
        # precondition against vacuity: the BODY must actually have moved
        # (zero body grads would make the equivalence below meaningless)
        body_moved = float(jnp.abs(
            want_p["blocks"][0]["w1"] - params["blocks"][0]["w1"]).max())
        assert body_moved > 1e-6, "vacuous: body gradients are zero"
        step = make_sp_train_step(mesh, cfg, lr=0.1)
        got_p, got_l = step(params, tokens, labels)
        np.testing.assert_allclose(float(got_l), float(want_l), rtol=2e-5)
        flat_w, _ = jax.tree_util.tree_flatten(want_p)
        flat_g, _ = jax.tree_util.tree_flatten(got_p)
        for w, g in zip(flat_w, flat_g):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-4, atol=2e-5)

    def test_training_reduces_loss(self):
        """A few sp steps actually learn (loss decreases monotonically-ish
        on a fixed batch)."""
        from bflc_demo_tpu.parallel.ring_attention import make_sp_train_step
        model = _model(seq_len=32)
        mesh = make_mesh((4,), (SP_AXIS,))
        rng = np.random.default_rng(6)
        tokens = _tokens(rng, 8, 32)
        labels = jnp.asarray(np.eye(model.config.num_classes,
                                    dtype=np.float32)[
            rng.integers(0, model.config.num_classes, 8)])
        # 15 steps at lr=0.1: the 5-step window the bar originally used is
        # init-sensitive — jax PRNG draws differ across versions, and some
        # inits transiently overshoot before descending (gradient EXACTNESS
        # is pinned separately by test_matches_single_device_step; this bar
        # is about learning, so give it a learning-scale window)
        step = make_sp_train_step(mesh, model.config, lr=0.1)
        params = self._rand_head(model.init_params(6), seed=6)
        losses = []
        for _ in range(15):
            params, loss = step(params, tokens, labels)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(losses)), losses


class TestDPSPTrainStep:
    """Composed data x sequence parallelism: batch shards over dp, sequence
    over sp; one SGD step equals the single-device step on the full batch
    (randomized head — zero-init head makes the check vacuous)."""

    @pytest.mark.parametrize("n_dp,n_sp", [(2, 4), (4, 2)])
    def test_matches_single_device_step(self, n_dp, n_sp):
        from bflc_demo_tpu.parallel.ring_attention import (
            make_dp_sp_train_step)
        model = _model(seq_len=32)
        cfg = model.config
        mesh = make_mesh((n_dp, n_sp), ("dp", SP_AXIS))
        rng = np.random.default_rng(12)
        tokens = _tokens(rng, 8, 32)
        labels = jnp.asarray(np.eye(cfg.num_classes, dtype=np.float32)[
            rng.integers(0, cfg.num_classes, 8)])
        params = model.init_params(12)
        params["head_w"] = jax.random.normal(
            jax.random.PRNGKey(12), params["head_w"].shape,
            jnp.float32) * 0.5

        def loss_fn(p):
            logits = transformer_forward(p, tokens, cfg)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.sum(labels * logp, axis=-1))

        want_l, g = jax.value_and_grad(loss_fn)(params)
        want_p = jax.tree_util.tree_map(lambda w, d: w - 0.1 * d, params, g)
        assert float(jnp.abs(want_p["blocks"][0]["w1"]
                             - params["blocks"][0]["w1"]).max()) > 1e-6

        step = make_dp_sp_train_step(mesh, cfg, lr=0.1)
        got_p, got_l = step(params, tokens, labels)
        np.testing.assert_allclose(float(got_l), float(want_l), rtol=2e-5)
        for (path, w), gg in zip(
                jax.tree_util.tree_flatten_with_path(want_p)[0],
                jax.tree_util.tree_leaves(got_p)):
            np.testing.assert_allclose(
                np.asarray(gg), np.asarray(w), rtol=5e-4, atol=5e-5,
                err_msg=jax.tree_util.keystr(path))
