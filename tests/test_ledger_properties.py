"""Property-based differential fuzzing of the ledger backends.

The C++ ledger and its Python mirror must be observationally identical under
ARBITRARY op sequences — not just the happy paths the unit tests script.
Hypothesis drives random protocol traffic (valid and invalid interleaved)
into both backends simultaneously and asserts lock-step equivalence of every
status code and every piece of observable state, plus the protocol
invariants the reference enforces via PBFT ordering (SURVEY.md §4
"property tests: epoch monotonicity, at-most-one-update-per-client-per-
round").
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property fuzzing needs the hypothesis package")
from hypothesis import given, settings, strategies as st  # noqa: E402

from bflc_demo_tpu.ledger import make_ledger, LedgerStatus, bindings
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3)

pytestmark = pytest.mark.skipif(not bindings.native_available(),
                                reason="native ledger unavailable")

ADDRS = [f"0x{i:03x}" for i in range(8)]
# register draws only 0..5 (client_num=6) so addresses 6-7 are GUARANTEED
# unregistered — uploads/scores from them always exercise the unknown-sender
# paths
ACTION = st.one_of(
    st.tuples(st.just("register"), st.integers(0, 5)),
    st.tuples(st.just("upload"), st.integers(0, 7), st.integers(-1, 1),
              st.integers(0, 255), st.integers(1, 500)),
    st.tuples(st.just("scores"), st.integers(0, 7), st.integers(-1, 1),
              st.integers(0, 100)),
    st.tuples(st.just("close"), ),
    st.tuples(st.just("force"), ),
    st.tuples(st.just("reseat"), st.lists(st.integers(0, 7), min_size=1,
                                          max_size=3)),
    st.tuples(st.just("commit"), st.integers(-1, 1), st.integers(0, 255)),
)


def _apply(led, action):
    kind = action[0]
    if kind == "register":
        return led.register_node(ADDRS[action[1]])
    if kind == "upload":
        _, actor, ep_off, payload, nsamp = action
        return led.upload_local_update(
            ADDRS[actor], bytes([payload]) * 32, nsamp, 1.25,
            led.epoch + ep_off)
    if kind == "scores":
        _, actor, ep_off, base = action
        k = led.update_count
        scores = [float(np.float32((base + j) / 101.0)) for j in range(k)]
        return led.upload_scores(ADDRS[actor], led.epoch + ep_off, scores)
    if kind == "close":
        return led.close_round()
    if kind == "force":
        return led.force_aggregate()
    if kind == "reseat":
        return led.reseat_committee([ADDRS[i] for i in action[1]])
    if kind == "commit":
        _, ep_off, payload = action
        return led.commit_model(bytes([payload]) * 32, led.epoch + ep_off)
    raise AssertionError(kind)


def _observe(led):
    return {
        "epoch": led.epoch,
        "registered": led.num_registered,
        "updates": led.update_count,
        "scores": led.score_count,
        "committee": led.committee(),
        "ready": led.aggregate_ready(),
        "closed": led.round_closed,
        "log_size": led.log_size(),
        "head": led.log_head(),
        "model": led.query_global_model(),
        # exact f32 equality — both backends compute in float32, so any
        # reduction-order divergence must surface, not be rounded away
        "loss": float(led.last_global_loss),
    }


@settings(max_examples=200, deadline=None)
@given(st.lists(ACTION, min_size=1, max_size=60))
def test_native_python_lockstep(actions):
    nat = make_ledger(CFG, backend="native")
    py = make_ledger(CFG, backend="python")
    for action in actions:
        st_nat = _apply(nat, action)
        st_py = _apply(py, action)
        assert st_nat == st_py, (action, st_nat, st_py)
        obs_n, obs_p = _observe(nat), _observe(py)
        assert obs_n == obs_p, (action, obs_n, obs_p)
    assert nat.verify_log() and py.verify_log()


@settings(max_examples=150, deadline=None)
@given(st.lists(ACTION, min_size=1, max_size=60))
def test_protocol_invariants(actions):
    led = make_ledger(CFG, backend="python")
    last_epoch = led.epoch
    uploaded_this_round = set()
    for action in actions:
        before_epoch = led.epoch
        status = _apply(led, action)
        # epoch moves forward only: genesis -> 0 on the client_num-th
        # registration (the FL start trigger, .cpp:175-186), +1 on commit
        assert led.epoch >= last_epoch
        if led.epoch != before_epoch:
            if before_epoch == CFG.genesis_epoch:
                assert action[0] == "register" and led.epoch == 0
                assert led.num_registered == CFG.client_num
            else:
                assert action[0] == "commit" and status == LedgerStatus.OK
                assert led.epoch == before_epoch + 1
                uploaded_this_round.clear()
                # post-commit the round state is reset
                assert led.update_count == 0 and led.score_count == 0
                assert not led.round_closed and not led.aggregate_ready()
        last_epoch = led.epoch
        # at most one accepted upload per client per round, cap respected
        if action[0] == "upload" and status == LedgerStatus.OK:
            assert action[1] not in uploaded_this_round
            uploaded_this_round.add(action[1])
        assert led.update_count <= CFG.needed_update_count
        # committee never exceeds comm_count
        assert len(led.committee()) <= CFG.comm_count
    assert led.verify_log()


@settings(max_examples=100, deadline=None)
@given(st.lists(ACTION, min_size=1, max_size=40))
def test_replay_reconstructs_any_state(actions):
    """Whatever traffic produced a ledger state, replaying its accepted-op
    log into a fresh replica reproduces it exactly (the replication
    contract — every op sequence, not just clean rounds)."""
    led = make_ledger(CFG, backend="python")
    for action in actions:
        _apply(led, action)
    replica = make_ledger(CFG, backend="python")
    for i in range(led.log_size()):
        assert replica.apply_op(led.log_op(i)) == LedgerStatus.OK
    assert _observe(led) == _observe(replica)
