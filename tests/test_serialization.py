"""Canonical serialization / hashing / store tests, plus round-trip
property coverage and the opt-in quantized delta encodings (data-plane
PR: utils.serialization.quantize_entries / dequantize_entries)."""

import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.comm import UpdateStore
from bflc_demo_tpu.utils import (canonical_bytes, hash_pytree, pack_pytree,
                                 unpack_pytree)
from bflc_demo_tpu.utils.serialization import (QSCALE_SUFFIX,
                                               TOPK_SUFFIX,
                                               densify_entries,
                                               dequantize_entries,
                                               pack_entries,
                                               pack_quantized,
                                               pack_sparse,
                                               quantize_entries,
                                               sparsify_entries,
                                               topk_count)


def tree():
    return {"W": jnp.arange(10, dtype=jnp.float32).reshape(5, 2),
            "b": jnp.ones(2, jnp.float32)}


def test_hash_deterministic_and_sensitive():
    t = tree()
    assert hash_pytree(t) == hash_pytree(tree())
    t2 = {"W": t["W"].at[0, 0].set(99.0), "b": t["b"]}
    assert hash_pytree(t2) != hash_pytree(t)
    # dtype-sensitive
    t3 = {"W": t["W"].astype(jnp.bfloat16), "b": t["b"]}
    assert hash_pytree(t3) != hash_pytree(t)
    # shape-sensitive beyond raw bytes
    t4 = {"W": t["W"].reshape(2, 5), "b": t["b"]}
    assert hash_pytree(t4) != hash_pytree(t)


def test_hash_ignores_dict_insertion_order():
    a = {"W": np.zeros((2, 2), np.float32), "b": np.ones(2, np.float32)}
    b = dict(reversed(list(a.items())))
    assert hash_pytree(a) == hash_pytree(b)


def test_pack_unpack_roundtrip():
    blob = pack_pytree(tree())
    flat = unpack_pytree(blob)
    assert set(flat) == {"['W']", "['b']"}
    np.testing.assert_array_equal(flat["['W']"], np.asarray(tree()["W"]))
    assert flat["['W']"].dtype == np.float32


def test_unpack_rejects_garbage():
    with pytest.raises(ValueError):
        unpack_pytree(b"not a blob")


def test_bfloat16_roundtrip():
    t = {"W": jnp.full((4, 4), 1.5, jnp.bfloat16)}
    flat = unpack_pytree(pack_pytree(t))
    arr = flat["['W']"]
    assert arr.dtype == np.asarray(t["W"]).dtype
    np.testing.assert_array_equal(arr, np.asarray(t["W"]))


class TestRoundTripProperties:
    """pack -> unpack -> pack is the identity on bytes; unpack preserves
    keys, shapes and dtypes — over the structural edge cases the wire
    actually carries."""

    def test_empty_tree(self):
        blob = pack_pytree({})
        assert unpack_pytree(blob) == {}
        assert hash_pytree({}) == hash_pytree({})

    def test_zero_d_arrays(self):
        t = {"s": np.float32(3.5), "n": np.int64(-7)}
        flat = unpack_pytree(pack_pytree(t))
        assert flat["['s']"].shape == () and flat["['n']"].shape == ()
        assert float(flat["['s']"]) == 3.5 and int(flat["['n']"]) == -7

    def test_zero_length_axis(self):
        t = {"e": np.zeros((0, 4), np.float32)}
        flat = unpack_pytree(pack_pytree(t))
        assert flat["['e']"].shape == (0, 4)
        assert flat["['e']"].dtype == np.float32

    @pytest.mark.parametrize("dtype", [
        np.float32, np.float64, np.float16, np.int8, np.int32,
        np.uint8, np.bool_])
    def test_dtype_preservation(self, dtype):
        arr = np.arange(6).reshape(2, 3).astype(dtype)
        flat = unpack_pytree(pack_pytree({"a": arr}))
        assert flat["['a']"].dtype == arr.dtype
        np.testing.assert_array_equal(flat["['a']"], arr)

    def test_pack_entries_unpack_identity(self):
        """The documented contract: pack_entries(unpack_pytree(b)) == b
        — content addresses agree across the network boundary."""
        t = {"W": np.linspace(-1, 1, 12, dtype=np.float32).reshape(3, 4),
             "b": np.zeros((4,), np.float32),
             "n": np.int32(9)}
        blob = pack_pytree(t)
        assert pack_entries(unpack_pytree(blob)) == blob

    def test_nested_structure_flattens_stably(self):
        t = {"layer": {"W": np.ones((2, 2), np.float32)},
             "head": [np.zeros(3, np.float32),
                      np.ones(3, np.float32)]}
        blob1, blob2 = pack_pytree(t), pack_pytree(t)
        assert blob1 == blob2
        flat = unpack_pytree(blob1)
        assert len(flat) == 3
        assert hash_pytree(t) == hash_pytree(t)


class TestQuantizedEncodings:
    def _flat(self):
        rng = np.random.default_rng(42)
        return {"['W']": rng.standard_normal((32, 8)).astype(np.float32),
                "['b']": np.zeros((8,), np.float32)}

    def test_f32_is_identity(self):
        flat = self._flat()
        assert quantize_entries(flat, "f32") == flat
        out = dequantize_entries(flat)
        for k in flat:
            np.testing.assert_array_equal(out[k], flat[k])

    def test_f16_roundtrip_error_bounded(self):
        flat = self._flat()
        out = dequantize_entries(quantize_entries(flat, "f16"))
        for k in flat:
            assert out[k].dtype == np.float32
            np.testing.assert_allclose(out[k], flat[k],
                                       atol=2e-3, rtol=1e-3)

    def test_i8_roundtrip_error_within_half_scale(self):
        flat = self._flat()
        q = quantize_entries(flat, "i8")
        assert q["['W']"].dtype == np.int8
        scale = float(np.asarray(q["['W']" + QSCALE_SUFFIX]))
        out = dequantize_entries(q)
        assert np.max(np.abs(out["['W']"] - flat["['W']"])) \
            <= scale / 2 + 1e-7

    def test_i8_zero_leaf_uses_unit_scale(self):
        q = quantize_entries({"['z']": np.zeros((4,), np.float32)}, "i8")
        assert float(np.asarray(q["['z']" + QSCALE_SUFFIX])) == 1.0
        out = dequantize_entries(q)
        np.testing.assert_array_equal(out["['z']"], np.zeros(4))

    def test_quantized_bytes_are_deterministic_and_hash_stable(self):
        t = {"W": self._flat()["['W']"]}
        for dtype in ("f16", "i8"):
            b1, b2 = pack_quantized(t, dtype), pack_quantized(t, dtype)
            assert b1 == b2
            # the quantized blob IS the canonical payload: unpack/repack
            # reproduces the exact signed bytes
            assert pack_entries(unpack_pytree(b1)) == b1

    def test_non_float_leaves_pass_through(self):
        flat = {"['n']": np.arange(4, dtype=np.int32)}
        for dtype in ("f16", "i8"):
            q = quantize_entries(flat, dtype)
            assert q["['n']"].dtype == np.int32
            assert "['n']" + QSCALE_SUFFIX not in q
            out = dequantize_entries(q)
            np.testing.assert_array_equal(out["['n']"], flat["['n']"])

    def test_honest_int8_tensor_without_scale_untouched(self):
        flat = {"['q']": np.arange(-3, 3, dtype=np.int8)}
        out = dequantize_entries(flat)
        assert out["['q']"].dtype == np.int8

    def test_unknown_dtype_rejected(self):
        with pytest.raises(ValueError, match="delta dtype"):
            quantize_entries({}, "f8")


class TestSparseEncodings:
    """Deterministic top-k sparsification (utils.serialization
    sparsify_entries / densify_entries / pack_sparse): round-trip,
    tie determinism, k edges, non-float passthrough, quantization
    composition, and malformed-#topk rejection."""

    def _flat(self, shape=(40, 25), seed=42):
        rng = np.random.default_rng(seed)
        return {"['W']": rng.standard_normal(shape).astype(np.float32)}

    def test_topk_roundtrip_keeps_exactly_the_topk(self):
        flat = self._flat()
        s = sparsify_entries(flat, 0.01)
        d = densify_entries(s)
        W = flat["['W']"].ravel()
        k = topk_count(W.size, 0.01)
        order = np.argsort(-np.abs(W), kind="stable")
        idx = np.sort(order[:k])
        got = d["['W']"]
        assert got.shape == flat["['W']"].shape
        assert got.dtype == np.float32
        np.testing.assert_array_equal(got.ravel()[idx], W[idx])
        assert np.all(np.delete(got.ravel(), idx) == 0.0)

    def test_tie_determinism_ascending_index(self):
        # duplicated magnitudes: the survivor set must be the EARLIEST
        # flat indices, and two encoders produce identical bytes
        flat = {"['t']": np.asarray([1.0, -1.0, 0.5, 1.0, -1.0],
                                    np.float32)}
        s = sparsify_entries(flat, 0.4)       # k = ceil(2) = 2
        rec = s["['t']" + TOPK_SUFFIX]
        assert list(rec[:2]) == [1, 5]        # ndim, shape
        assert list(rec[2:]) == [0, 1]        # ties -> lowest indices
        assert pack_entries(sparsify_entries(dict(flat), 0.4)) == \
            pack_entries(s)

    def test_k_zero_edge(self):
        s = sparsify_entries({"['x']": np.ones((6,), np.float32)}, 0.0)
        assert s["['x']"].size == 0
        d = densify_entries(s)
        assert d["['x']"].shape == (6,) and np.all(d["['x']"] == 0)

    def test_k_full_edges_stay_dense(self):
        flat = self._flat(shape=(3,))
        # density 1.0 is the identity; k >= size keeps the leaf dense
        assert sparsify_entries(flat, 1.0) == flat
        s = sparsify_entries(flat, 0.9)       # ceil(2.7) = 3 = size
        assert TOPK_SUFFIX not in "".join(s)
        np.testing.assert_array_equal(s["['W']"], flat["['W']"])
        # a 0-d leaf can never sparsify below one entry
        s0 = sparsify_entries({"['s']": np.float32(2.5)}, 0.01)
        assert "['s']" + TOPK_SUFFIX not in s0

    def test_non_float_leaf_passthrough(self):
        flat = {"['n']": np.arange(9, dtype=np.int32)}
        s = sparsify_entries(flat, 0.1)
        assert "['n']" + TOPK_SUFFIX not in s
        np.testing.assert_array_equal(
            densify_entries(s)["['n']"], flat["['n']"])

    def test_densify_identity_on_dense(self):
        flat = self._flat()
        out = densify_entries(flat)
        np.testing.assert_array_equal(out["['W']"], flat["['W']"])

    def test_pack_sparse_dense_pin_and_determinism(self):
        t = {"W": self._flat()["['W']"], "b": np.ones(4, np.float32)}
        from bflc_demo_tpu.utils.serialization import pack_pytree
        assert pack_sparse(t, 1.0) == pack_pytree(t)
        b1, b2 = pack_sparse(t, 0.05), pack_sparse(t, 0.05)
        assert b1 == b2
        assert pack_entries(unpack_pytree(b1)) == b1

    def test_quantization_composes(self):
        t = {"W": self._flat()["['W']"]}
        blob = pack_sparse(t, 0.05, "i8")
        flat = unpack_pytree(blob)
        assert flat["['W']"].dtype == np.int8
        assert ("['W']" + QSCALE_SUFFIX) in flat
        assert ("['W']" + TOPK_SUFFIX) in flat
        d = densify_entries(dequantize_entries(flat))
        assert d["['W']"].shape == (40, 25)
        assert d["['W']"].dtype == np.float32
        # the sparse x i8 blob is smaller than i8 alone
        assert len(blob) < len(pack_quantized(t, "i8"))

    def _sparse(self):
        return sparsify_entries(self._flat(), 0.05)

    def _with_rec(self, mutate):
        s = dict(self._sparse())
        key = "['W']" + TOPK_SUFFIX
        rec = s[key].copy()
        s[key] = mutate(rec)
        return s

    def test_malformed_out_of_bounds_rejected(self):
        def oob(rec):
            rec[-1] = 10 ** 6
            return rec
        with pytest.raises(ValueError, match="out of bounds"):
            densify_entries(self._with_rec(oob))

    def test_malformed_duplicate_and_unsorted_rejected(self):
        def dup(rec):
            rec[4] = rec[3]
            return rec
        with pytest.raises(ValueError, match="ascending"):
            densify_entries(self._with_rec(dup))

        def swap(rec):
            rec[3], rec[4] = rec[4].copy(), rec[3].copy()
            return rec
        with pytest.raises(ValueError, match="ascending"):
            densify_entries(self._with_rec(swap))

    def test_malformed_oversized_count_rejected(self):
        # more claimed values+indices than the leaf holds
        s = dict(self._sparse())
        key = "['W']" + TOPK_SUFFIX
        rec = s[key]
        ndim = int(rec[0])
        big = np.arange(2000, dtype=np.uint32)
        s[key] = np.concatenate([rec[:1 + ndim].copy(), big])
        s["['W']"] = np.zeros(2000, np.float32)
        with pytest.raises(ValueError, match="out of bounds"):
            densify_entries(s)

    def test_malformed_dtype_and_orphan_rejected(self):
        s = dict(self._sparse())
        key = "['W']" + TOPK_SUFFIX
        s[key] = s[key].astype(np.int64)
        with pytest.raises(ValueError, match="uint32"):
            densify_entries(s)
        s2 = {key: self._sparse()[key]}       # record, no values leaf
        with pytest.raises(ValueError, match="values leaf"):
            densify_entries(s2)

    def test_malformed_count_mismatch_rejected(self):
        s = dict(self._sparse())
        s["['W']"] = np.append(s["['W']"], np.float32(1.0))
        with pytest.raises(ValueError, match="indices for"):
            densify_entries(s)

    def test_giant_claimed_shape_rejected_before_allocation(self):
        # a ~100-byte hostile record must not be able to size a
        # multi-GB np.zeros: the claimed dense size is refused first
        s = dict(self._sparse())
        key = "['W']" + TOPK_SUFFIX
        rec = s[key].copy()
        rec[1] = rec[2] = np.uint32(2 ** 31 - 1)    # shape (2^31, 2^31)
        s[key] = rec
        with pytest.raises(ValueError, match="claimed dense size"):
            densify_entries(s)

    def test_many_records_cannot_sum_past_the_allocation_cap(self):
        # per-record caps alone are defeatable: thousands of tiny
        # records each claiming an individually-legal large shape must
        # refuse CUMULATIVELY, not allocate leaf by leaf
        s = {}
        for i in range(8):
            k = f"['L{i}']"
            s[k] = np.zeros(0, np.float32)
            s[k + TOPK_SUFFIX] = np.asarray(
                [2, 8192, 8192], np.uint32)     # 64M elems each, legal
        with pytest.raises(ValueError, match="claimed dense size"):
            densify_entries(s)


def test_store_integrity():
    s = UpdateStore()
    h = s.put(tree())
    assert s.contains(h)
    got = s.get(h)
    np.testing.assert_array_equal(np.asarray(got["W"]),
                                  np.asarray(tree()["W"]))
    s.drop(h)
    assert not s.contains(h)
    assert len(s) == 0
