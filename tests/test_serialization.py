"""Canonical serialization / hashing / store tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.comm import UpdateStore
from bflc_demo_tpu.utils import (canonical_bytes, hash_pytree, pack_pytree,
                                 unpack_pytree)


def tree():
    return {"W": jnp.arange(10, dtype=jnp.float32).reshape(5, 2),
            "b": jnp.ones(2, jnp.float32)}


def test_hash_deterministic_and_sensitive():
    t = tree()
    assert hash_pytree(t) == hash_pytree(tree())
    t2 = {"W": t["W"].at[0, 0].set(99.0), "b": t["b"]}
    assert hash_pytree(t2) != hash_pytree(t)
    # dtype-sensitive
    t3 = {"W": t["W"].astype(jnp.bfloat16), "b": t["b"]}
    assert hash_pytree(t3) != hash_pytree(t)
    # shape-sensitive beyond raw bytes
    t4 = {"W": t["W"].reshape(2, 5), "b": t["b"]}
    assert hash_pytree(t4) != hash_pytree(t)


def test_hash_ignores_dict_insertion_order():
    a = {"W": np.zeros((2, 2), np.float32), "b": np.ones(2, np.float32)}
    b = dict(reversed(list(a.items())))
    assert hash_pytree(a) == hash_pytree(b)


def test_pack_unpack_roundtrip():
    blob = pack_pytree(tree())
    flat = unpack_pytree(blob)
    assert set(flat) == {"['W']", "['b']"}
    np.testing.assert_array_equal(flat["['W']"], np.asarray(tree()["W"]))
    assert flat["['W']"].dtype == np.float32


def test_unpack_rejects_garbage():
    with pytest.raises(ValueError):
        unpack_pytree(b"not a blob")


def test_bfloat16_roundtrip():
    t = {"W": jnp.full((4, 4), 1.5, jnp.bfloat16)}
    flat = unpack_pytree(pack_pytree(t))
    arr = flat["['W']"]
    assert arr.dtype == np.asarray(t["W"]).dtype
    np.testing.assert_array_equal(arr, np.asarray(t["W"]))


def test_store_integrity():
    s = UpdateStore()
    h = s.put(tree())
    assert s.contains(h)
    got = s.get(h)
    np.testing.assert_array_equal(np.asarray(got["W"]),
                                  np.asarray(tree()["W"]))
    s.drop(h)
    assert not s.contains(h)
    assert len(s) == 0
