"""Canonical serialization / hashing / store tests, plus round-trip
property coverage and the opt-in quantized delta encodings (data-plane
PR: utils.serialization.quantize_entries / dequantize_entries)."""

import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.comm import UpdateStore
from bflc_demo_tpu.utils import (canonical_bytes, hash_pytree, pack_pytree,
                                 unpack_pytree)
from bflc_demo_tpu.utils.serialization import (QSCALE_SUFFIX,
                                               dequantize_entries,
                                               pack_entries,
                                               pack_quantized,
                                               quantize_entries)


def tree():
    return {"W": jnp.arange(10, dtype=jnp.float32).reshape(5, 2),
            "b": jnp.ones(2, jnp.float32)}


def test_hash_deterministic_and_sensitive():
    t = tree()
    assert hash_pytree(t) == hash_pytree(tree())
    t2 = {"W": t["W"].at[0, 0].set(99.0), "b": t["b"]}
    assert hash_pytree(t2) != hash_pytree(t)
    # dtype-sensitive
    t3 = {"W": t["W"].astype(jnp.bfloat16), "b": t["b"]}
    assert hash_pytree(t3) != hash_pytree(t)
    # shape-sensitive beyond raw bytes
    t4 = {"W": t["W"].reshape(2, 5), "b": t["b"]}
    assert hash_pytree(t4) != hash_pytree(t)


def test_hash_ignores_dict_insertion_order():
    a = {"W": np.zeros((2, 2), np.float32), "b": np.ones(2, np.float32)}
    b = dict(reversed(list(a.items())))
    assert hash_pytree(a) == hash_pytree(b)


def test_pack_unpack_roundtrip():
    blob = pack_pytree(tree())
    flat = unpack_pytree(blob)
    assert set(flat) == {"['W']", "['b']"}
    np.testing.assert_array_equal(flat["['W']"], np.asarray(tree()["W"]))
    assert flat["['W']"].dtype == np.float32


def test_unpack_rejects_garbage():
    with pytest.raises(ValueError):
        unpack_pytree(b"not a blob")


def test_bfloat16_roundtrip():
    t = {"W": jnp.full((4, 4), 1.5, jnp.bfloat16)}
    flat = unpack_pytree(pack_pytree(t))
    arr = flat["['W']"]
    assert arr.dtype == np.asarray(t["W"]).dtype
    np.testing.assert_array_equal(arr, np.asarray(t["W"]))


class TestRoundTripProperties:
    """pack -> unpack -> pack is the identity on bytes; unpack preserves
    keys, shapes and dtypes — over the structural edge cases the wire
    actually carries."""

    def test_empty_tree(self):
        blob = pack_pytree({})
        assert unpack_pytree(blob) == {}
        assert hash_pytree({}) == hash_pytree({})

    def test_zero_d_arrays(self):
        t = {"s": np.float32(3.5), "n": np.int64(-7)}
        flat = unpack_pytree(pack_pytree(t))
        assert flat["['s']"].shape == () and flat["['n']"].shape == ()
        assert float(flat["['s']"]) == 3.5 and int(flat["['n']"]) == -7

    def test_zero_length_axis(self):
        t = {"e": np.zeros((0, 4), np.float32)}
        flat = unpack_pytree(pack_pytree(t))
        assert flat["['e']"].shape == (0, 4)
        assert flat["['e']"].dtype == np.float32

    @pytest.mark.parametrize("dtype", [
        np.float32, np.float64, np.float16, np.int8, np.int32,
        np.uint8, np.bool_])
    def test_dtype_preservation(self, dtype):
        arr = np.arange(6).reshape(2, 3).astype(dtype)
        flat = unpack_pytree(pack_pytree({"a": arr}))
        assert flat["['a']"].dtype == arr.dtype
        np.testing.assert_array_equal(flat["['a']"], arr)

    def test_pack_entries_unpack_identity(self):
        """The documented contract: pack_entries(unpack_pytree(b)) == b
        — content addresses agree across the network boundary."""
        t = {"W": np.linspace(-1, 1, 12, dtype=np.float32).reshape(3, 4),
             "b": np.zeros((4,), np.float32),
             "n": np.int32(9)}
        blob = pack_pytree(t)
        assert pack_entries(unpack_pytree(blob)) == blob

    def test_nested_structure_flattens_stably(self):
        t = {"layer": {"W": np.ones((2, 2), np.float32)},
             "head": [np.zeros(3, np.float32),
                      np.ones(3, np.float32)]}
        blob1, blob2 = pack_pytree(t), pack_pytree(t)
        assert blob1 == blob2
        flat = unpack_pytree(blob1)
        assert len(flat) == 3
        assert hash_pytree(t) == hash_pytree(t)


class TestQuantizedEncodings:
    def _flat(self):
        rng = np.random.default_rng(42)
        return {"['W']": rng.standard_normal((32, 8)).astype(np.float32),
                "['b']": np.zeros((8,), np.float32)}

    def test_f32_is_identity(self):
        flat = self._flat()
        assert quantize_entries(flat, "f32") == flat
        out = dequantize_entries(flat)
        for k in flat:
            np.testing.assert_array_equal(out[k], flat[k])

    def test_f16_roundtrip_error_bounded(self):
        flat = self._flat()
        out = dequantize_entries(quantize_entries(flat, "f16"))
        for k in flat:
            assert out[k].dtype == np.float32
            np.testing.assert_allclose(out[k], flat[k],
                                       atol=2e-3, rtol=1e-3)

    def test_i8_roundtrip_error_within_half_scale(self):
        flat = self._flat()
        q = quantize_entries(flat, "i8")
        assert q["['W']"].dtype == np.int8
        scale = float(np.asarray(q["['W']" + QSCALE_SUFFIX]))
        out = dequantize_entries(q)
        assert np.max(np.abs(out["['W']"] - flat["['W']"])) \
            <= scale / 2 + 1e-7

    def test_i8_zero_leaf_uses_unit_scale(self):
        q = quantize_entries({"['z']": np.zeros((4,), np.float32)}, "i8")
        assert float(np.asarray(q["['z']" + QSCALE_SUFFIX])) == 1.0
        out = dequantize_entries(q)
        np.testing.assert_array_equal(out["['z']"], np.zeros(4))

    def test_quantized_bytes_are_deterministic_and_hash_stable(self):
        t = {"W": self._flat()["['W']"]}
        for dtype in ("f16", "i8"):
            b1, b2 = pack_quantized(t, dtype), pack_quantized(t, dtype)
            assert b1 == b2
            # the quantized blob IS the canonical payload: unpack/repack
            # reproduces the exact signed bytes
            assert pack_entries(unpack_pytree(b1)) == b1

    def test_non_float_leaves_pass_through(self):
        flat = {"['n']": np.arange(4, dtype=np.int32)}
        for dtype in ("f16", "i8"):
            q = quantize_entries(flat, dtype)
            assert q["['n']"].dtype == np.int32
            assert "['n']" + QSCALE_SUFFIX not in q
            out = dequantize_entries(q)
            np.testing.assert_array_equal(out["['n']"], flat["['n']"])

    def test_honest_int8_tensor_without_scale_untouched(self):
        flat = {"['q']": np.arange(-3, 3, dtype=np.int8)}
        out = dequantize_entries(flat)
        assert out["['q']"].dtype == np.int8

    def test_unknown_dtype_rejected(self):
        with pytest.raises(ValueError, match="delta dtype"):
            quantize_entries({}, "f8")


def test_store_integrity():
    s = UpdateStore()
    h = s.put(tree())
    assert s.contains(h)
    got = s.get(h)
    np.testing.assert_array_equal(np.asarray(got["W"]),
                                  np.asarray(tree()["W"]))
    s.drop(h)
    assert not s.contains(h)
    assert len(s) == 0
