"""Closed-loop compression: error-feedback residual lifecycle, the
certified genome-update op (opcode 13), and the adaptive-density drill.

Three planes under test:

1. `client.process_runtime._DeltaEncoder` — the client-LOCAL error-
   feedback accumulator (Seide et al. 2014 / Karimireddy et al. 2019,
   PAPERS.md).  It is deliberately NOT part of the protocol genome:
   armed or not, wire bytes are the plain sparse/quantized protocol, so
   the tests pin (a) disarmed == stateless byte-for-byte, (b) residual
   lifecycle resets on every model-lineage discontinuity (rejoin,
   async base-epoch jump, cell re-home — all of which surface as a
   base-epoch gap at the encoder), (c) determinism of the full
   EF + i8 + density-0.01 composition.

2. The genome-update op itself: proposed by the writer on the fixed
   decision rule (control.loop.decide), re-derived by every replica,
   refused BAD_ARG on any mismatch — so the effective-knob schedule is
   certified state, not writer fiat.

3. The closed loop end to end: a scripted multi-round federation where
   density actually moves mid-run with ZERO honest-path refusals, a
   fresh replica replays the whole op stream to the same head, and a
   lying writer is refused at the quorum (ValidatorNode drill).
"""

from __future__ import annotations

import hashlib
import struct

import numpy as np
import pytest

from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
from bflc_demo_tpu.ledger.base import (OP_AUPLOAD, OP_GENOME, OP_UPLOAD,
                                       encode_genome_op)
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils.serialization import (densify_entries,
                                               dequantize_entries,
                                               pack_pytree, pack_sparse,
                                               restore_pytree,
                                               unpack_pytree)


def _tree(rng, scale=1.0):
    return {"W1": (scale * rng.standard_normal((24, 16))
                   ).astype(np.float32),
            "b1": (scale * rng.standard_normal(16)).astype(np.float32),
            "W2": (scale * rng.standard_normal((16, 3))
                   ).astype(np.float32)}


def _decode(template, blob):
    return restore_pytree(template, densify_entries(
        dequantize_entries(unpack_pytree(blob))))


# ------------------------------------------------ error-feedback encoder
class TestErrorFeedbackEncoder:
    def _encoder(self, cfg, template, monkeypatch, armed=True):
        monkeypatch.setenv("BFLC_ERROR_FEEDBACK", "1" if armed else "0")
        from bflc_demo_tpu.client.process_runtime import _DeltaEncoder
        return _DeltaEncoder(cfg, template)

    def test_disarmed_is_stateless_passthrough(self, monkeypatch):
        """EF off (the default) pins the static trajectory byte-for-
        byte: every encode equals the stateless encoder's output and no
        residual state accumulates."""
        from bflc_demo_tpu.client.process_runtime import _encode_delta
        cfg = ProtocolConfig(delta_density=0.05).validate()
        rng = np.random.default_rng(0)
        t = _tree(rng, 0.0)
        enc = self._encoder(cfg, t, monkeypatch, armed=False)
        for ep in range(3):
            d = _tree(rng)
            assert enc.encode(d, base_epoch=ep) == _encode_delta(d, cfg)
        assert enc._residual is None

    def test_first_encode_matches_stateless(self, monkeypatch):
        from bflc_demo_tpu.client.process_runtime import _encode_delta
        cfg = ProtocolConfig(delta_density=0.05,
                             delta_dtype="i8").validate()
        rng = np.random.default_rng(1)
        t = _tree(rng, 0.0)
        enc = self._encoder(cfg, t, monkeypatch)
        d = _tree(rng)
        assert enc.encode(d, base_epoch=0) == _encode_delta(d, cfg)

    def test_residual_recurrence_is_exact(self, monkeypatch):
        """residual_t = compensated_t - decode(encode(compensated_t)),
        with compensated_t = delta_t + residual_{t-1} — the EF-SGD
        memory recursion, checked bit-level against the ONE decode
        chain the admission path runs."""
        cfg = ProtocolConfig(delta_density=0.05).validate()
        rng = np.random.default_rng(2)
        t = _tree(rng, 0.0)
        enc = self._encoder(cfg, t, monkeypatch)
        residual = {k: np.zeros_like(v) for k, v in t.items()}
        for ep in range(4):
            d = _tree(rng)
            comp = {k: d[k] + residual[k] for k in d}
            blob = enc.encode(d, base_epoch=ep)
            got = _decode(t, blob)
            residual = {k: comp[k].astype(np.float32)
                        - np.asarray(got[k], np.float32) for k in d}
            for k in d:
                np.testing.assert_array_equal(enc._residual[k],
                                              residual[k])

    def test_reset_on_base_epoch_jump(self, monkeypatch):
        """Any lineage discontinuity — crash + rejoin, committee-duty
        epoch gap, async base-epoch jump, cell re-home — surfaces as
        base_epoch != last_base + 1, and the residual MUST die with the
        old lineage: the post-jump encode is byte-identical to a fresh
        encoder's (no stale-model correction leaks into the new one)."""
        cfg = ProtocolConfig(delta_density=0.05).validate()
        rng = np.random.default_rng(3)
        t = _tree(rng, 0.0)
        enc = self._encoder(cfg, t, monkeypatch)
        deltas = [_tree(rng) for _ in range(4)]
        enc.encode(deltas[0], base_epoch=0)
        enc.encode(deltas[1], base_epoch=1)
        assert enc._residual is not None
        # epoch 2..4 missed (rejoin at 5): residual resets
        fresh = self._encoder(cfg, t, monkeypatch)
        assert enc.encode(deltas[2], base_epoch=5) == \
            fresh.encode(deltas[2], base_epoch=5)
        # ...and the NEW lineage accumulates normally from there
        assert enc.encode(deltas[3], base_epoch=6) == \
            fresh.encode(deltas[3], base_epoch=6)
        assert enc._residual is not None

    def test_contiguous_epochs_keep_residual(self, monkeypatch):
        cfg = ProtocolConfig(delta_density=0.05).validate()
        rng = np.random.default_rng(4)
        t = _tree(rng, 0.0)
        enc = self._encoder(cfg, t, monkeypatch)
        d = _tree(rng)
        b0 = enc.encode(d, base_epoch=0)
        b1 = enc.encode(d, base_epoch=1)      # contiguous: compensated
        fresh = self._encoder(cfg, t, monkeypatch)
        fresh.encode(d, base_epoch=0)
        assert b1 == fresh.encode(d, base_epoch=1)
        assert b0 != b1  # the residual actually changed the encode

    def test_ef_catches_up_on_persistent_signal(self, monkeypatch):
        """The point of EF: under a persistent gradient direction, the
        accumulated reconstruction (sum of decoded deltas) converges to
        the true sum — the residual carries everything top-k dropped
        into later rounds.  The stateless encoder's error grows
        linearly; EF's stays bounded."""
        cfg = ProtocolConfig(delta_density=0.05).validate()
        rng = np.random.default_rng(5)
        t = _tree(rng, 0.0)
        signal = _tree(rng)                   # fixed direction
        enc = self._encoder(cfg, t, monkeypatch)
        from bflc_demo_tpu.client.process_runtime import _encode_delta
        got_ef = {k: np.zeros_like(v) for k, v in t.items()}
        got_sl = {k: np.zeros_like(v) for k, v in t.items()}
        rounds = 32                           # > 1/density: the residual
        for ep in range(rounds):              # cycle flushes every coord
            de = _decode(t, enc.encode(signal, base_epoch=ep))
            ds = _decode(t, _encode_delta(signal, cfg))
            for k in t:
                got_ef[k] += np.asarray(de[k], np.float32)
                got_sl[k] += np.asarray(ds[k], np.float32)
        err = lambda got: sum(  # noqa: E731
            float(np.linalg.norm(rounds * signal[k] - got[k]))
            for k in t)
        # measured: EF error plateaus (~0.28x at 32 rounds and still
        # falling) while the stateless error grows linearly forever
        assert err(got_ef) < 0.35 * err(got_sl)

    def test_ef_i8_density_001_composition_byte_stable(self, monkeypatch):
        """The headline composition (EF + i8 + density 0.01) is fully
        deterministic: two encoders fed the same delta stream emit
        identical byte sequences, and every blob admits through the one
        decode chain."""
        cfg = ProtocolConfig(delta_density=0.01,
                             delta_dtype="i8").validate()
        rng = np.random.default_rng(6)
        t = {"W": np.zeros((64, 40), np.float32),
             "b": np.zeros(40, np.float32)}
        deltas = [{"W": rng.standard_normal((64, 40)).astype(np.float32),
                   "b": rng.standard_normal(40).astype(np.float32)}
                  for _ in range(3)]
        a = self._encoder(cfg, t, monkeypatch)
        b = self._encoder(cfg, t, monkeypatch)
        for ep, d in enumerate(deltas):
            ba = a.encode(d, base_epoch=ep)
            assert ba == b.encode({k: v.copy() for k, v in d.items()},
                                  base_epoch=ep)
            _decode(t, ba)                    # admissible

    def test_density_override_tracks_effective_knob(self, monkeypatch):
        """The encoder takes the round's served eff_density (the
        adaptive loop's output) per call — a knob change between rounds
        changes the blob geometry without touching residual state."""
        cfg = ProtocolConfig(delta_density=0.08).validate()
        rng = np.random.default_rng(7)
        t = {"W": np.zeros(4000, np.float32)}
        enc = self._encoder(cfg, t, monkeypatch)
        d = {"W": rng.standard_normal(4000).astype(np.float32)}
        b_hi = enc.encode(d, base_epoch=0, density=0.08)
        b_lo = enc.encode(d, base_epoch=1, density=0.02)
        assert len(b_lo) < len(b_hi)
        assert enc._residual is not None


# --------------------------------------------- genome op / replica rules
class TestGenomeOp:
    def _armed_cfg(self, **kw):
        base = dict(delta_density=0.05, adapt_every=2,
                    density_floor=0.01)
        base.update(kw)
        return ProtocolConfig(**base).validate()

    def test_adapt_requires_sparse_genome(self):
        with pytest.raises(ValueError, match="SPARSE"):
            ProtocolConfig(adapt_every=2).validate()

    def test_genome_op_refused_unless_armed(self):
        led = make_ledger(ProtocolConfig(delta_density=0.05).validate(),
                          backend="python")
        op = encode_genome_op(1, 0.025, 0, 1.0, 0.0, 0.01)
        assert led.apply_op(op) == LedgerStatus.BAD_ARG

    def test_legacy_pin_disarms_loop(self, monkeypatch):
        monkeypatch.setenv("BFLC_ADAPT_LEGACY", "1")
        from bflc_demo_tpu.ledger.base import adapt_enabled
        assert not adapt_enabled(self._armed_cfg())

    def test_decision_rule_is_pure_and_clamped(self):
        from bflc_demo_tpu.control.loop import decide
        cfg = self._armed_cfg()
        kw = dict(density_floor=cfg.density_floor,
                  density_cap=cfg.delta_density, staleness_cap=0)
        # converging (low disagreement): density halves toward floor
        d, _ = decide(0.05, 0, 1.0, 0.5, 0.01, **kw)
        assert d == pytest.approx(0.025)
        # unhealthy: density doubles, clamped at the genome's cap
        d2, _ = decide(0.04, 0, 1.0, 0.5, 0.5, **kw)
        assert d2 == pytest.approx(cfg.delta_density)
        # floor clamp
        d3, _ = decide(cfg.density_floor, 0, 1.0, 0.5, 0.01, **kw)
        assert d3 == pytest.approx(cfg.density_floor)

    def test_genome_f32_fields_roundtrip_replay(self):
        """The op stores f32; a replica re-encoding from parsed fields
        must reproduce the writer's bytes exactly (else honest replay
        would diverge on x87/f64 drift)."""
        op = encode_genome_op(7, 0.012500000186264515, 3,
                              1.2345678, 0.87654321, 0.111111111)
        ep = struct.unpack_from("<q", op, 1)[0]
        nd, = struct.unpack_from("<f", op, 9)
        ns, = struct.unpack_from("<q", op, 13)
        un, dr, di = struct.unpack_from("<fff", op, 21)
        assert encode_genome_op(ep, nd, ns, un, dr, di) == op


# ----------------------------------------------- the closed loop, end-to-end
def _run_closed_loop_drill(adapt_every=1, rounds=4, dim=240, seed=11):
    """Scripted multi-round federation over server._dispatch (no
    sockets, no auth — the certification logic under test is identical;
    see tests/test_sparse.py for the pattern).  Clients encode at the
    SERVED eff_density each round, exactly as process_runtime does.
    Returns (server, per-epoch densities, blob_by_hash)."""
    from bflc_demo_tpu.comm.ledger_service import LedgerServer
    cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=4,
                         needed_update_count=4, delta_density=0.08,
                         adapt_every=adapt_every,
                         density_floor=0.01).validate()
    base = np.random.default_rng(seed).standard_normal(dim) \
        .astype(np.float32)
    server = LedgerServer(cfg, pack_pytree({"W": np.zeros(dim,
                                                          np.float32)}),
                          require_auth=False, stall_timeout_s=3600.0,
                          verbose=False)
    addrs = [f"c{i:02d}" for i in range(cfg.client_num)]
    for a in addrs:
        assert server._dispatch("register", {"addr": a})["ok"]
    densities, blob_by_hash = [], {}
    for _ in range(rounds):
        ep = server.ledger.epoch
        st = server._dispatch("state", {"addr": addrs[0]})
        # exactly what process_runtime does: encode at the served knob,
        # genome config when the loop is disarmed (legacy pin drill)
        eff = st.get("eff_density", cfg.delta_density)
        densities.append((ep, eff))
        committee = server._dispatch("committee", {})["committee"]
        trainers = sorted(a for a in addrs if a not in committee)
        for a in trainers[:cfg.needed_update_count]:
            d = (base + 0.3 * np.random.default_rng(
                [addrs.index(a), ep, seed]).standard_normal(dim)
                 ).astype(np.float32)
            blob = pack_sparse({"W": d}, eff)
            h = hashlib.sha256(blob)
            blob_by_hash[h.digest()] = blob
            r = server._dispatch("upload", {
                "addr": a, "blob": blob, "hash": h.hexdigest(),
                "n": 10, "cost": 1.0, "epoch": ep})
            assert r["ok"], (a, ep, r)       # ZERO honest-path refusals
        row = [1.0 - 0.05 * j
               for j in range(cfg.needed_update_count)]
        for a in committee:
            r = server._dispatch("scores", {"addr": a, "epoch": ep,
                                            "scores": row})
            assert r["ok"], (a, ep, r)
        assert server.ledger.epoch == ep + 1
    return server, densities, blob_by_hash


class TestClosedLoopDrill:
    def test_density_moves_with_zero_refusals_and_replays(self):
        server, densities, _ = _run_closed_loop_drill()
        try:
            led = server.ledger
            assert led.genome_epoch is not None
            moved = {e for _, e in densities}
            assert len(moved) >= 2, densities  # knob changed mid-run
            assert min(moved) < 0.08
            # the NEXT round's state poll serves the post-commit knob
            # (a genome op lands atomically with its round's commit, so
            # the last in-loop poll lags it by one transition)
            st = server._dispatch("state", {"addr": "c00"})
            assert st["eff_density"] == pytest.approx(
                led.effective_density)
            # a fresh replica replays the FULL stream (incl. opcode 13)
            rep = make_ledger(server.cfg, backend="python")
            for j in range(led.log_size()):
                assert rep.apply_op(led.log_op(j)) == LedgerStatus.OK, j
            assert rep.log_head() == led.log_head()
            assert rep.effective_density == led.effective_density
            assert rep.effective_staleness == led.effective_staleness
            # info reply surfaces the live knobs for the tools plane
            info = server._dispatch("info", {})
            assert info["eff_density"] == pytest.approx(
                led.effective_density)
            assert info["genome_epoch"] == led.genome_epoch
        finally:
            server.close()

    def test_adapt_legacy_pins_static_knobs(self, monkeypatch):
        monkeypatch.setenv("BFLC_ADAPT_LEGACY", "1")
        server, densities, _ = _run_closed_loop_drill(rounds=3)
        try:
            assert all(e == pytest.approx(0.08) for _, e in densities)
            for j in range(server.ledger.log_size()):
                assert server.ledger.log_op(j)[0] != OP_GENOME
        finally:
            server.close()

    def test_lying_writer_refused_at_quorum(self):
        """A writer claiming a knob transition its certified telemetry
        does not support is refused by the validator quorum: the
        validator replays the honest prefix, then refuses BOTH a wrong-
        output lie (density the rule never produced) and a wrong-input
        lie (disagreement that mismatches its own re-derivation) —
        while the honest op at the same position still passes."""
        from bflc_demo_tpu.comm.bft import ValidatorNode
        from bflc_demo_tpu.comm.identity import Wallet
        server, _, blob_by_hash = _run_closed_loop_drill()
        node = None
        try:
            led = server.ledger
            node = ValidatorNode(server.cfg,
                                 Wallet.from_seed(b"closed-loop-vtest"),
                                 0, require_auth=False)
            gpos = None
            for j in range(led.log_size()):
                op = led.log_op(j)
                if op[0] == OP_GENOME and gpos is None:
                    gpos = j
                    break
                auth = {}
                if op[0] in (OP_UPLOAD, OP_AUPLOAD):
                    (slen,) = struct.unpack_from("<q", op, 1)
                    h = op[1 + 8 + slen:1 + 8 + slen + 32]
                    auth = {"blob": blob_by_hash[h].hex()}
                r = node._validate({"i": j, "op": op.hex(),
                                    "auth": auth})
                assert r["ok"], (j, r)
            assert gpos is not None
            op = led.log_op(gpos)
            ep = struct.unpack_from("<q", op, 1)[0]
            nd, = struct.unpack_from("<f", op, 9)
            ns, = struct.unpack_from("<q", op, 13)
            un, dr, di = struct.unpack_from("<fff", op, 21)
            lie_out = encode_genome_op(ep, nd * 2.0, ns, un, dr, di)
            r = node._validate({"i": gpos, "op": lie_out.hex()})
            assert not r["ok"], r
            lie_in = encode_genome_op(ep, nd, ns, un, dr, di + 0.5)
            r = node._validate({"i": gpos, "op": lie_in.hex()})
            assert not r["ok"], r
            r = node._validate({"i": gpos, "op": op.hex()})
            assert r["ok"], r
        finally:
            if node is not None:
                node.close()
            server.close()

    def test_snapshot_state_roundtrips_genome_tail(self):
        """Canonical state (what snapshots certify and rejoiners state-
        sync from) carries the effective knobs: a ledger restored from
        mid-run state continues on the SAME schedule."""
        from bflc_demo_tpu.ledger.snapshot import restore_snapshot
        server, _, _ = _run_closed_loop_drill()
        try:
            led = server.ledger
            rep = restore_snapshot(led.encode_state(), server.cfg,
                                   led.log_size(), led.log_head())
            assert rep.effective_density == led.effective_density
            assert rep.effective_staleness == led.effective_staleness
            assert rep.genome_epoch == led.genome_epoch
            assert rep.encode_state() == led.encode_state()
        finally:
            server.close()


# ------------------------------------- mid-run knob-change differential
class TestDensityTransition:
    def test_mixed_density_round_rederives_byte_identical(self):
        """tools/check_reduction_spec.py's closed-loop leg, tier-1
        sized: one aggregation holding blobs encoded at different
        densities/codecs (the mid-run genome transition) must re-derive
        to the writer's committed hash on both validator paths."""
        import sys
        sys.path.insert(0, "tools")
        from check_reduction_spec import \
            run_density_transition_differential
        out = run_density_transition_differential(trials=4, seed=5,
                                                  max_n=10)
        assert out["mismatches"] == [], out
