"""End-to-end integration: the reference demo's acceptance run, deterministic.

Reproduces SURVEY.md §6's reproduction target (test-acc ≈ 0.92 by round ~10 on
config 1) as an automated test — the reference verified this by reading
screenshots (SURVEY.md §4); we assert it.
"""

import numpy as np
import pytest

from bflc_demo_tpu.client import run_federated
from bflc_demo_tpu.data import load_occupancy, iid_shards
from bflc_demo_tpu.data.occupancy import occupancy_source
from bflc_demo_tpu.ledger import bindings
from bflc_demo_tpu.models import make_softmax_regression
from bflc_demo_tpu.protocol import DEFAULT_PROTOCOL

BACKENDS = ["python"] + (["native"] if bindings.native_available() else [])

# the 0.90-by-round-10 bar is a property of the REAL UCI distribution
# (reference sponsor: 0.9214 at epoch ~9).  On hosts without the CSV the
# seeded synthetic stand-in runs instead; its raw-feature trajectory is
# worse-conditioned (oscillates around its peak), so the acceptance band
# calibrates to the stand-in's own measured plateau — still well above
# the 0.787 majority-class floor, and the REAL bar re-arms automatically
# wherever the CSV exists (see data.occupancy.occupancy_source).
ACC_BAR = 0.90 if occupancy_source() == "csv" else 0.85


@pytest.fixture(scope="module")
def occupancy():
    xtr, ytr, xte, yte = load_occupancy()
    return iid_shards(xtr, ytr, DEFAULT_PROTOCOL.client_num), (xte, yte)


@pytest.mark.parametrize("backend", BACKENDS)
def test_config1_reaches_reference_accuracy(occupancy, backend):
    shards, test_set = occupancy
    res = run_federated(make_softmax_regression(), shards, test_set,
                        DEFAULT_PROTOCOL, rounds=10,
                        ledger_backend=backend, seed=0)
    assert res.rounds_completed == 10
    # reference: 0.9214 at sponsor epoch 009 (imgs/runtime.jpg)
    assert res.best_accuracy() >= ACC_BAR, res.accuracy_history
    # ledger log covers: 20 registers + 10*(10 uploads + 4 scores + 1 commit)
    assert res.ledger_log_size == 20 + 10 * 15


def test_mesh_runtime_reaches_reference_accuracy(occupancy):
    """The device-resident round loop (one XLA program per round) hits the
    same target, with ledger/device decisions cross-checked every round."""
    from bflc_demo_tpu.client import run_federated_mesh
    shards, test_set = occupancy
    res = run_federated_mesh(make_softmax_regression(), shards, test_set,
                             DEFAULT_PROTOCOL, rounds=10, seed=0)
    assert res.best_accuracy() >= ACC_BAR, res.accuracy_history
    assert res.ledger_log_size == 20 + 10 * 15


def test_mesh_runtime_batched_dispatch(occupancy):
    """R-rounds-per-dispatch optimistic execution: device samples/elects/
    decides for R rounds in one program; the ledger replays and audits each
    round (divergence would raise inside run_federated_mesh)."""
    from bflc_demo_tpu.client import run_federated_mesh
    shards, test_set = occupancy
    res = run_federated_mesh(make_softmax_regression(), shards, test_set,
                             DEFAULT_PROTOCOL, rounds=10,
                             rounds_per_dispatch=5, seed=0)
    assert res.best_accuracy() >= ACC_BAR, res.accuracy_history
    assert res.ledger_log_size == 20 + 10 * 15
    assert res.ledger.verify_log()
    # deterministic: same seed, same batched run -> same log head
    res2 = run_federated_mesh(make_softmax_regression(), shards, test_set,
                              DEFAULT_PROTOCOL, rounds=10,
                              rounds_per_dispatch=5, seed=0)
    assert res2.ledger_log_head == res.ledger_log_head


def test_deterministic_replay(occupancy):
    """Same seed -> identical ledger log head (scores, ranking, election and
    committed model hashes all bit-equal across runs)."""
    shards, test_set = occupancy
    r1 = run_federated(make_softmax_regression(), shards, test_set,
                       DEFAULT_PROTOCOL, rounds=3, seed=5)
    r2 = run_federated(make_softmax_regression(), shards, test_set,
                       DEFAULT_PROTOCOL, rounds=3, seed=5)
    assert r1.ledger_log_head == r2.ledger_log_head
    np.testing.assert_array_equal(
        np.asarray(r1.final_params["W"]), np.asarray(r2.final_params["W"]))


def test_different_seed_different_path(occupancy):
    shards, test_set = occupancy
    r1 = run_federated(make_softmax_regression(), shards, test_set,
                       DEFAULT_PROTOCOL, rounds=2, seed=1)
    r2 = run_federated(make_softmax_regression(), shards, test_set,
                       DEFAULT_PROTOCOL, rounds=2, seed=2)
    # visit order differs -> different first-come-10 sets -> different logs
    assert r1.ledger_log_head != r2.ledger_log_head
