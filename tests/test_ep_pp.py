"""Expert-parallel (MoE) and pipeline-parallel tests on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bflc_demo_tpu.models.transformer import (make_transformer_classifier,
                                              transformer_forward)
from bflc_demo_tpu.parallel.mesh import make_mesh
from bflc_demo_tpu.parallel.ep import (make_ep_train_step, shard_moe_params,
                                       moe_partition_specs)
from bflc_demo_tpu.parallel.pp import (bubble_at_memory_budget,
                                       make_pp_1f1b_train_step,
                                       make_pp_transformer_forward,
                                       schedule_stats, shard_pp_params,
                                       stack_blocks)


def _tokens(rng, b, s, vocab=100):
    x = rng.integers(1, vocab, (b, s)).astype(np.int32)
    lengths = rng.integers(s // 2, s + 1, b)
    for i in range(b):
        x[i, lengths[i]:] = 0
    return jnp.asarray(x)


class TestMoE:
    def test_moe_forward_and_train(self):
        model = make_transformer_classifier(vocab_size=100, seq_len=16,
                                            num_classes=3, dim=32, depth=2,
                                            heads=2, moe_experts=4)
        rng = np.random.default_rng(0)
        toks = _tokens(rng, 4, 16)
        params = model.init_params(0)
        assert params["blocks"][0]["we1"].shape == (4, 32, 128)
        logits = model.apply(params, toks)
        assert logits.shape == (4, 3)
        # the head is zero-init (FL genesis convention) which blocks
        # upstream grads on step one — give it values for the grad check
        params = dict(params)
        params["head_w"] = jnp.asarray(
            rng.standard_normal((32, 3)), jnp.float32) * 0.1
        g = jax.grad(lambda p: jnp.sum(model.apply(p, toks) ** 2))(params)
        leaves = jax.tree_util.tree_leaves(g)
        assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
        # router gradient is live (the mixture actually routes)
        assert float(jnp.abs(g["blocks"][0]["router"]).max()) > 0

    def test_ep_step_matches_single_device(self):
        model = make_transformer_classifier(vocab_size=100, seq_len=16,
                                            num_classes=3, dim=32, depth=1,
                                            heads=2, moe_experts=4)
        cfg = model.config
        mesh = make_mesh((2, 4), ("dp", "ep"))
        rng = np.random.default_rng(1)
        toks = _tokens(rng, 8, 16)
        labels = jnp.asarray(np.eye(3, dtype=np.float32)[
            rng.integers(0, 3, 8)])
        params = model.init_params(1)

        def loss_fn(p):
            logits = transformer_forward(p, toks, cfg)
            return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits),
                                     -1))
        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
        ref_new = jax.tree_util.tree_map(lambda w, g: w - 0.1 * g,
                                         params, ref_grads)

        step = make_ep_train_step(mesh, model.apply, cfg, lr=0.1)
        sharded = shard_moe_params(params, mesh)
        assert sharded["blocks"][0]["we1"].sharding.spec == \
            P("ep", None, None)
        new_params, loss = step(sharded, toks, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(new_params["blocks"][0]["we1"]),
            np.asarray(ref_new["blocks"][0]["we1"]), rtol=2e-4, atol=2e-5)

    def test_ep_guards(self):
        dense = make_transformer_classifier(vocab_size=100, seq_len=16,
                                            num_classes=2, dim=16, depth=1,
                                            heads=2)
        mesh = make_mesh((2, 4), ("dp", "ep"))
        with pytest.raises(ValueError):
            make_ep_train_step(mesh, dense.apply, dense.config, lr=0.1)
        moe3 = make_transformer_classifier(vocab_size=100, seq_len=16,
                                           num_classes=2, dim=16, depth=1,
                                           heads=2, moe_experts=3)
        with pytest.raises(ValueError):
            make_ep_train_step(mesh, moe3.apply, moe3.config, lr=0.1)


class TestPipeline:
    @pytest.mark.parametrize("n_pp,m", [(2, 2), (2, 4), (4, 4)])
    def test_pp_matches_single_device(self, n_pp, m):
        model = make_transformer_classifier(vocab_size=100, seq_len=16,
                                            num_classes=3, dim=32, depth=4,
                                            heads=2)
        cfg = model.config
        mesh = make_mesh((n_pp,), ("pp",))
        rng = np.random.default_rng(2)
        toks = _tokens(rng, 8, 16)
        params = model.init_params(2)
        want = transformer_forward(params, toks, cfg)
        fwd = make_pp_transformer_forward(mesh, cfg, microbatches=m)
        got = fwd(shard_pp_params(params, mesh), toks)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_pp_params_actually_sharded(self):
        model = make_transformer_classifier(vocab_size=100, seq_len=16,
                                            num_classes=3, dim=32, depth=4,
                                            heads=2)
        mesh = make_mesh((4,), ("pp",))
        sharded = shard_pp_params(model.init_params(0), mesh)
        assert sharded["blocks"]["wq"].shape[0] == 4       # stacked depth
        assert sharded["blocks"]["wq"].sharding.spec[0] == "pp"
        assert sharded["embed"].sharding.spec == P()

    def test_pp_gradients_flow(self):
        model = make_transformer_classifier(vocab_size=100, seq_len=8,
                                            num_classes=2, dim=16, depth=2,
                                            heads=2)
        cfg = model.config
        mesh = make_mesh((2,), ("pp",))
        rng = np.random.default_rng(3)
        toks = _tokens(rng, 4, 8)
        y = jnp.asarray(np.eye(2, dtype=np.float32)[[0, 1, 0, 1]])
        fwd = make_pp_transformer_forward(mesh, cfg, microbatches=2)
        stacked = stack_blocks(model.init_params(3))
        # non-zero head so gradients reach the blocks (zero-init genesis
        # head blocks upstream grads on step one)
        stacked["head_w"] = jnp.asarray(
            rng.standard_normal((16, 2)), jnp.float32) * 0.1

        def loss(p):
            logits = fwd(p, toks)
            return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), -1))

        g = jax.grad(loss)(stacked)
        leaves = jax.tree_util.tree_leaves(g)
        assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
        assert float(jnp.abs(g["blocks"]["wq"]).max()) > 0


class Test1F1B:
    @pytest.mark.parametrize("n_pp,m", [(2, 4), (4, 8)])
    def test_1f1b_step_matches_single_device(self, n_pp, m):
        """One 1F1B SGD step == one single-device SGD step: same loss, same
        updated parameters (block, embed, and head leaves checked)."""
        model = make_transformer_classifier(vocab_size=100, seq_len=16,
                                            num_classes=3, dim=32, depth=4,
                                            heads=2)
        cfg = model.config
        mesh = make_mesh((n_pp,), ("pp",))
        rng = np.random.default_rng(4)
        toks = _tokens(rng, m * 2, 16)
        labels = jnp.asarray(np.eye(3, dtype=np.float32)[
            rng.integers(0, 3, m * 2)])
        params = model.init_params(4)
        params = dict(params)
        params["head_w"] = jnp.asarray(
            rng.standard_normal((32, 3)), jnp.float32) * 0.1

        def loss_fn(p):
            logits = transformer_forward(p, toks, cfg)
            return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits),
                                     -1))
        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
        ref_new = jax.tree_util.tree_map(lambda w, g: w - 0.1 * g,
                                         params, ref_grads)
        ref_new_stacked = stack_blocks(ref_new)

        step = make_pp_1f1b_train_step(mesh, cfg, microbatches=m, lr=0.1)
        new_params, loss = step(shard_pp_params(params, mesh), toks, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for key in ("wq", "w1"):
            np.testing.assert_allclose(
                np.asarray(new_params["blocks"][key]),
                np.asarray(ref_new_stacked["blocks"][key]),
                rtol=2e-4, atol=2e-5)
        for key in ("embed", "pos", "head_w", "head_b"):
            np.testing.assert_allclose(
                np.asarray(new_params[key]), np.asarray(ref_new_stacked[key]),
                rtol=2e-4, atol=2e-5)

    def test_1f1b_memory_and_bubble_advantage(self):
        """The schedule model the module docstring claims: at >= 4
        microbatches per stage, 1F1B's live-activation window stays at
        2p-1 (< GPipe's M), and at EQUAL activation memory 1F1B's bubble
        fraction is strictly below GPipe's."""
        for p in (2, 4, 8):
            m = 4 * p
            g = schedule_stats("gpipe", m, p)
            f = schedule_stats("1f1b", m, p)
            assert f["peak_live_microbatches"] == 2 * p - 1
            assert f["peak_live_microbatches"] < \
                g["peak_live_microbatches"] == m
            # equal-memory comparison: both schedules get 2p-1 live slots;
            # GPipe must shrink M to fit, 1F1B runs the full M
            budget = 2 * p - 1
            assert bubble_at_memory_budget("1f1b", budget, p, m) < \
                bubble_at_memory_budget("gpipe", budget, p, m)

    def test_1f1b_guards(self):
        model = make_transformer_classifier(vocab_size=100, seq_len=8,
                                            num_classes=2, dim=16, depth=3,
                                            heads=2)
        mesh = make_mesh((2,), ("pp",))
        with pytest.raises(ValueError):
            make_pp_1f1b_train_step(mesh, model.config, microbatches=2,
                                    lr=0.1)

    def test_pp_depth_guard(self):
        model = make_transformer_classifier(vocab_size=100, seq_len=8,
                                            num_classes=2, dim=16, depth=3,
                                            heads=2)
        mesh = make_mesh((2,), ("pp",))
        with pytest.raises(ValueError):
            make_pp_transformer_forward(mesh, model.config, microbatches=2)
