"""Sparse certified upload deltas (ISSUE 13; utils.serialization
sparsify/densify, --delta-density).

The properties under test, end to end through real servers:

- **hash parity across aggregation legs**: a scripted config-1-shaped
  sync round with sparse uploads commits the SAME model hash under the
  legacy host loop, the spec host leg and the compiled mesh leg
  (golden-pinned), and the async FedBuff drain carries sparse blobs
  through opcode 10 unchanged;
- **the dense pin**: density 1.0 (the default) and BFLC_SPARSE_LEGACY=1
  commit byte-identical hashes to each other (and the dense chain is
  untouched by construction — tests/test_meshagg.py's golden pins keep
  covering pre-PR bytes);
- **arrival-order determinism**: the sparse cell-partial bridge blob is
  a pure function of the admitted SET (sorted-sender accumulation +
  deterministic top-k), so permuting arrival cannot move the certified
  hash;
- **admission + validator re-execution**: a malformed `#topk` blob is
  refused by the writer as a schema error AND by a density-armed
  validator quorum via the blob-carrying auth evidence
  (comm.bft.check_sparse_upload_op) — a colluding writer cannot certify
  one;
- **density-aware health**: at density 0.01 an honest fleet produces
  zero WARN/CRIT verdicts while a sign-flip/scale attacker is still
  CRIT within 2 rounds of turning (obs.health density wiring).
"""

import hashlib
import struct

import numpy as np
import pytest

from bflc_demo_tpu.obs import health as obs_health
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils.serialization import (TOPK_SUFFIX,
                                               densify_entries,
                                               pack_entries,
                                               pack_pytree, pack_sparse,
                                               sparse_enabled,
                                               unpack_pytree)

# golden digests for the scripted sparse/dense rounds below: any drift
# in the sparse encode, the densify inverse, or the merge arithmetic
# fails here (the DENSE golden doubles as the density-1.0 pin)
GOLDEN_SPARSE_MODEL = ("2044a0aa0a2fb09858cd5e8b1b6bf410"
                       "60a84571b7a6cc91c09135e92cf1d8c4")
GOLDEN_DENSE_MODEL = ("1139b686390e0c76c9c2d12173d41669"
                      "594da3550f7b5ffd56a08ce176f33683")


def _sign(w, kind, epoch, payload):
    from bflc_demo_tpu.comm.identity import _op_bytes
    return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()


def _tree(rng, scale=1.0):
    return {"W1": (rng.standard_normal((24, 16)) * scale
                   ).astype(np.float32),
            "b1": (rng.standard_normal((16,)) * scale
                   ).astype(np.float32),
            "W2": (rng.standard_normal((16, 3)) * scale
                   ).astype(np.float32)}


def _sync_round_model_hash(density: float,
                           legacy_blobs: bool = False) -> str:
    """Scripted config-1 sync round through a real LedgerServer with
    density-armed uploads; returns the committed model hash."""
    from bflc_demo_tpu.comm.identity import provision_wallets
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    cfg = ProtocolConfig(client_num=20, comm_count=4, aggregate_count=6,
                         needed_update_count=10, learning_rate=0.05,
                         batch_size=16,
                         delta_density=density).validate()
    rng = np.random.default_rng(13)
    blob0 = pack_pytree(_tree(rng))
    wallets, _ = provision_wallets(20, b"sparse-parity-seed")
    srv = LedgerServer(cfg, blob0)
    srv.start()
    cl = CoordinatorClient(srv.host, srv.port)
    try:
        for w in wallets:
            assert cl.request("register", addr=w.address,
                              pubkey=w.public_bytes.hex(),
                              tag=_sign(w, "register", 0, b""))["ok"]
        committee = set(cl.request("committee")["committee"])
        trainers = [w for w in wallets if w.address not in committee]
        for i, w in enumerate(trainers[:10]):
            t = _tree(np.random.default_rng(300 + i), 0.1)
            blob = (pack_pytree(t) if legacy_blobs
                    else pack_sparse(t, density))
            d = hashlib.sha256(blob).digest()
            payload = d + struct.pack("<qd", 20 + i, 1.0 + 0.05 * i)
            r = cl.request("upload", addr=w.address, blob=blob,
                           hash=d.hex(), n=20 + i, cost=1.0 + 0.05 * i,
                           epoch=0, tag=_sign(w, "upload", 0, payload))
            assert r["ok"], r
        for j, w in enumerate([w for w in wallets
                               if w.address in committee]):
            row = [0.5 + 0.01 * (j + u) for u in range(10)]
            payload = struct.pack("<10d", *row)
            r = cl.request("scores", addr=w.address, epoch=0,
                           scores=row,
                           tag=_sign(w, "scores", 0, payload))
            assert r["ok"] or r.get("status") == "WRONG_EPOCH", r
        assert cl.request("info")["epoch"] == 1
        return cl.request("model")["hash"]
    finally:
        cl.close()
        srv.close()


class TestSparseHashParity:
    """Acceptance pins: sparse uploads commit the SAME certified model
    hash on every aggregation leg, and the dense protocol is pinned
    byte-for-byte under density 1.0 / BFLC_SPARSE_LEGACY=1."""

    def test_sparse_round_hash_identical_across_legs(self, monkeypatch):
        monkeypatch.setenv("BFLC_MESH_AGG_LEGACY", "1")
        monkeypatch.delenv("BFLC_MESH_AGG_MIN", raising=False)
        legacy = _sync_round_model_hash(0.05)
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "1")
        mesh = _sync_round_model_hash(0.05)
        assert legacy == mesh == GOLDEN_SPARSE_MODEL

    def test_density_one_and_legacy_pin_are_the_dense_chain(
            self, monkeypatch):
        monkeypatch.delenv("BFLC_SPARSE_LEGACY", raising=False)
        dense = _sync_round_model_hash(1.0)
        assert dense == GOLDEN_DENSE_MODEL
        # BFLC_SPARSE_LEGACY=1: a density-configured fleet pins dense
        # bytes — clients upload dense, the writer admits dense
        monkeypatch.setenv("BFLC_SPARSE_LEGACY", "1")
        pinned = _sync_round_model_hash(0.05, legacy_blobs=True)
        assert pinned == GOLDEN_DENSE_MODEL

    def test_sparse_rejected_when_opted_out(self):
        """Density 1.0 (the default): a sparse blob dies at the door —
        its #topk entries are schema garbage to a dense fleet."""
        from bflc_demo_tpu.comm.ledger_service import LedgerServer
        g = _tree(np.random.default_rng(0))
        srv = LedgerServer(ProtocolConfig().validate(), pack_pytree(g),
                           require_auth=False, stall_timeout_s=3600.0)
        try:
            err, flat = srv._decode_delta(pack_sparse(g, 0.05))
            assert "mismatch" in err and flat is None
        finally:
            srv.close()

    def test_async_drain_carries_sparse_blobs(self, monkeypatch):
        """Opcode-10 aupload with sparse blobs: admission densifies,
        the FedBuff drain commits, hashes agree across meshagg legs."""
        from bflc_demo_tpu.comm.identity import (_op_bytes,
                                                 provision_wallets)
        from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                       LedgerServer)
        from bflc_demo_tpu.ledger.base import ascores_sign_payload

        def drain_hash():
            cfg = ProtocolConfig(client_num=8, comm_count=2,
                                 aggregate_count=2,
                                 needed_update_count=4,
                                 learning_rate=0.05, batch_size=16,
                                 async_buffer=4, max_staleness=4,
                                 delta_density=0.1).validate()
            rng = np.random.default_rng(12)
            blob0 = pack_pytree(_tree(rng))
            wallets, _ = provision_wallets(8, b"sparse-async-parity")
            srv = LedgerServer(cfg, blob0)
            srv.start()
            cl = CoordinatorClient(srv.host, srv.port)
            try:
                for w in wallets:
                    assert cl.request(
                        "register", addr=w.address,
                        pubkey=w.public_bytes.hex(),
                        tag=_sign(w, "register", 0, b""))["ok"]
                committee = set(cl.request("committee")["committee"])
                trainers = [w for w in wallets
                            if w.address not in committee]
                comm_ws = [w for w in wallets
                           if w.address in committee]

                def aupload(i, w, base):
                    blob = pack_sparse(
                        _tree(np.random.default_rng(400 + i), 0.1),
                        cfg.delta_density)
                    d = hashlib.sha256(blob).digest()
                    payload = d + struct.pack("<qd", 10 + i, 1.0)
                    return cl.request(
                        "aupload", addr=w.address, blob=blob,
                        hash=d.hex(), n=10 + i, cost=1.0,
                        base_epoch=base,
                        tag=_sign(w, "aupload", base, payload))

                for i, w in enumerate(trainers[:3]):
                    assert aupload(i, w, 0)["ok"]
                au = cl.request("aupdates")
                pairs = [(u["aseq"], 0.5 + 0.1 * u["aseq"])
                         for u in au["updates"]]
                w = comm_ws[0]
                assert cl.request(
                    "ascores", addr=w.address,
                    pairs=[[a, s] for a, s in pairs],
                    tag=w.sign(_op_bytes(
                        "ascores", w.address, 0,
                        ascores_sign_payload(pairs))).hex())["ok"]
                r = aupload(3, trainers[3], 0)
                assert r["ok"] and r["epoch"] == 1, r
                return cl.request("model")["hash"]
            finally:
                cl.close()
                srv.close()

        monkeypatch.setenv("BFLC_MESH_AGG_LEGACY", "1")
        monkeypatch.delenv("BFLC_MESH_AGG_MIN", raising=False)
        legacy = drain_hash()
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "1")
        mesh = drain_hash()
        assert legacy == mesh


class TestSparseCellBridge:
    """hier: members upload sparse, the cell re-sparsifies its partial
    for the bridge hop, the root densifies — arrival-order independent
    and registry-bounded exactly like the dense bridge."""

    def _admitted(self, n=5):
        keys = ["['W']", "['b']"]
        shapes = {"['W']": (24, 16), "['b']": (16,)}
        out = []
        for i in range(n):
            r = np.random.default_rng(i)
            flat = {k: r.standard_normal(shapes[k]).astype(np.float32)
                    for k in keys}
            out.append((f"0x{i:040x}", flat, 10 + i, 0.5))
        return out

    def test_bridge_blob_arrival_order_independent(self):
        import random

        from bflc_demo_tpu.hier.partial import cell_partial, partial_blob
        admitted = self._admitted()
        ev = b"\x07" * 32
        p1, n1, _ = cell_partial(admitted)
        blob1 = partial_blob(p1, 1, n1, ev, density=0.05)
        shuffled = list(admitted)
        random.Random(9).shuffle(shuffled)
        p2, n2, _ = cell_partial(shuffled)
        assert partial_blob(p2, 1, n2, ev, density=0.05) == blob1
        # density 1.0 keeps the pre-sparse bridge bytes
        assert partial_blob(p1, 1, n1, ev, density=1.0) == \
            partial_blob(p1, 1, n1, ev)

    def test_root_admits_sparse_partial_and_refuses_malformed(self):
        from bflc_demo_tpu.comm.ledger_service import LedgerServer
        from bflc_demo_tpu.hier.partial import (cell_partial,
                                                partial_blob,
                                                split_cellmeta)
        admitted = self._admitted()
        partial, n, _ = cell_partial(admitted)
        ev = b"\x07" * 32
        blob = partial_blob(partial, 1, n, ev, density=0.05)
        g = {"W": np.zeros((24, 16), np.float32),
             "b": np.zeros((16,), np.float32)}
        cfg = ProtocolConfig(client_num=6, comm_count=2,
                             aggregate_count=2, needed_update_count=4,
                             delta_density=0.05).validate()
        srv = LedgerServer(cfg, pack_pytree(g), require_auth=False,
                           cell_registry={"agg1": (1, 10)},
                           stall_timeout_s=3600.0)
        try:
            err, p = srv._decode_cell_partial("agg1", blob, n)
            assert err == "", err
            assert p["['W']"].shape == (24, 16)
            # the #cellmeta evidence rode the sparse blob intact
            _, meta = split_cellmeta(densify_entries(
                unpack_pytree(blob)))
            assert meta == (1, n, ev)
            # malformed #topk inside a cell partial dies at admission
            flat = dict(unpack_pytree(blob))
            key = [k for k in flat if k.endswith(TOPK_SUFFIX)][0]
            rec = flat[key].copy()
            rec[-1] = 10 ** 7
            flat[key] = rec
            err2, p2 = srv._decode_cell_partial(
                "agg1", pack_entries(flat), n)
            assert "undecodable" in err2 and p2 is None
        finally:
            srv.close()


class TestValidatorSparseReExecution:
    """A density-armed validator quorum re-executes sparse admission
    off the blob-carrying auth evidence: malformed #topk blobs (or
    missing/forged evidence) are refused — a colluding writer cannot
    certify one."""

    def _op_and_blob(self, good=True):
        from bflc_demo_tpu.ledger.base import encode_upload_op
        t = _tree(np.random.default_rng(5), 0.1)
        flat = unpack_pytree(pack_sparse(t, 0.05))
        if not good:
            key = [k for k in flat if k.endswith(TOPK_SUFFIX)][0]
            rec = flat[key].copy()
            rec[-1] = 10 ** 7
            flat = dict(flat)
            flat[key] = rec
        blob = pack_entries(flat)
        op = encode_upload_op("0xabc", hashlib.sha256(blob).digest(),
                              10, 1.0, 0)
        return op, blob

    def test_check_sparse_upload_op_refusals(self):
        from bflc_demo_tpu.comm.bft import check_sparse_upload_op
        op, blob = self._op_and_blob(good=True)
        assert check_sparse_upload_op(op, {"blob": blob.hex()}) == ""
        bop, bblob = self._op_and_blob(good=False)
        assert "densify" in check_sparse_upload_op(
            bop, {"blob": bblob.hex()})
        # missing evidence: a density-armed quorum requires the blob
        assert "without blob evidence" in \
            check_sparse_upload_op(op, {})
        # evidence that does not hash to the op's payload hash
        other = pack_pytree(_tree(np.random.default_rng(6)))
        assert "payload hash" in check_sparse_upload_op(
            op, {"blob": other.hex()})
        # non-upload ops pass through untouched
        from bflc_demo_tpu.ledger.base import encode_register_op
        assert check_sparse_upload_op(encode_register_op("0xabc"),
                                      {}) == ""

    def test_validator_refuses_malformed_topk_vote(self):
        """Integration: ValidatorNode._validate refuses the vote with
        SPARSE status before touching its replica (the refusal is
        independent of ledger state, so a colluding writer cannot
        sequence its way around it)."""
        from bflc_demo_tpu.comm.bft import ValidatorNode
        from bflc_demo_tpu.comm.identity import Wallet
        cfg = ProtocolConfig(client_num=6, comm_count=2,
                             aggregate_count=2, needed_update_count=4,
                             delta_density=0.05).validate()
        node = ValidatorNode(cfg, Wallet.from_seed(b"sparse-vtest"), 0,
                             require_auth=False)
        try:
            op, blob = self._op_and_blob(good=False)
            r = node._validate({"i": 0, "op": op.hex(),
                                "auth": {"blob": blob.hex()}})
            assert not r["ok"] and r["status"] == "SPARSE", r
            r2 = node._validate({"i": 0, "op": op.hex()})
            assert not r2["ok"] and r2["status"] == "SPARSE", r2
            # a well-formed sparse op passes the sparse gate (whatever
            # the replica then says about epoch/role is its own check)
            gop, gblob = self._op_and_blob(good=True)
            r3 = node._validate({"i": 0, "op": gop.hex(),
                                 "auth": {"blob": gblob.hex()}})
            assert r3.get("status") != "SPARSE", r3
        finally:
            node.close()

    def test_dense_quorum_ignores_sparse_gate(self):
        from bflc_demo_tpu.comm.bft import ValidatorNode
        from bflc_demo_tpu.comm.identity import Wallet
        node = ValidatorNode(ProtocolConfig().validate(),
                             Wallet.from_seed(b"dense-vtest"), 0,
                             require_auth=False)
        try:
            assert not node._sparse
        finally:
            node.close()


@pytest.fixture
def enabled_registry():
    was, role = obs_metrics.REGISTRY.enabled, obs_metrics.REGISTRY.role
    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "writer"
    yield obs_metrics.REGISTRY
    obs_metrics.REGISTRY.enabled = was
    obs_metrics.REGISTRY.role = role


def _delta_for(client: int, epoch: int, base: np.ndarray,
               dim: int) -> np.ndarray:
    rng = np.random.default_rng([client, epoch, 4321])
    return (base + 0.3 * rng.standard_normal(dim)).astype(np.float32)


def _run_sparse_drill(rounds: int, attacker: str, attack_from: int,
                      density: float = 0.01, dim: int = 400):
    """The health drill at density 0.01: scripted config-1 federation
    against a real LedgerServer dispatch surface, every upload a
    pack_sparse blob (k = ceil(density * dim) survivors).  Returns
    (health records, server) — the caller closes it."""
    from bflc_demo_tpu.comm.ledger_service import LedgerServer
    cfg = ProtocolConfig(delta_density=density).validate()
    rng = np.random.default_rng(99)
    base = rng.standard_normal(dim).astype(np.float32)
    blob0 = pack_pytree({"W": np.zeros(dim, np.float32)})
    server = LedgerServer(cfg, blob0, require_auth=False,
                          stall_timeout_s=3600.0)
    addrs = [f"c{i:02d}" for i in range(cfg.client_num)]
    for a in addrs:
        assert server._dispatch("register", {"addr": a})["ok"]
    for _ in range(rounds):
        ep = server.ledger.epoch
        committee = server._dispatch("committee", {})["committee"]
        trainers = sorted(a for a in addrs if a not in committee)
        uploaders = [a for a in trainers
                     if a != attacker][:cfg.needed_update_count - 1]
        uploaders.append(attacker)
        for a in uploaders:
            d = _delta_for(addrs.index(a), ep, base, dim)
            if a == attacker and ep >= attack_from:
                d = (-20.0 * d).astype(np.float32)
            blob = pack_sparse({"W": d}, density)
            r = server._dispatch("upload", {
                "addr": a, "blob": blob,
                "hash": hashlib.sha256(blob).hexdigest(),
                "n": 10, "cost": 1.0, "epoch": ep})
            assert r["ok"], (a, r)
        row = [1.0 - 0.05 * j for j in range(cfg.needed_update_count)]
        for a in committee:
            r = server._dispatch("scores", {"addr": a, "epoch": ep,
                                            "scores": row})
            assert r["ok"], (a, r)
        assert server.ledger.epoch == ep + 1, "round did not commit"
    assert server._health is not None
    return list(server._health.records), server


class TestSparseHealthDrill:
    """The density-awareness satellite: honest sparse deltas (zero_frac
    ~ 1 - density) never page; a sign-flip/scale attacker still does."""

    ROUNDS = 6
    ATTACK_FROM = 3

    def test_honest_sparse_fleet_zero_false_verdicts(
            self, enabled_registry, monkeypatch):
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        records, server = _run_sparse_drill(
            self.ROUNDS, attacker="c19", attack_from=10 ** 9)
        # the monitor judged with the protocol density
        assert server._health.density == pytest.approx(0.01)
        server.close()
        assert len(records) == self.ROUNDS
        assert all(r["verdict"] == "ok" for r in records), \
            [(r["epoch"], r["verdict"],
              [s for s in r["senders"] if s["level"] != "ok"])
             for r in records if r["verdict"] != "ok"]
        # honest sparse deltas really do sit near 1 - density
        zfs = [s["zero_frac"] for r in records for s in r["senders"]]
        assert min(zfs) > 0.9

    def test_sign_flip_attacker_still_crit_within_two_rounds(
            self, enabled_registry, monkeypatch):
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        records, server = _run_sparse_drill(
            self.ROUNDS, attacker="c19", attack_from=self.ATTACK_FROM)
        server.close()
        crit_epochs = [
            r["epoch"] for r in records
            if any(s["sender"] == "c19" and s["level"] == "crit"
                   for s in r["senders"])]
        assert crit_epochs, "attacker never went CRIT"
        assert min(crit_epochs) <= self.ATTACK_FROM + 1
        # and no honest sender ever CRITs on the attack leg
        for r in records:
            for s in r["senders"]:
                if s["sender"] != "c19":
                    assert s["level"] != "crit", (r["epoch"], s)


class TestSparseFleetEgress:
    """Slow fleet leg: a real 20-process federation at density 0.01
    moves an order of magnitude fewer upload bytes into the writer
    than the dense leg, while still training (the full benchmark
    artifact is eval.benchmarks.sparse_config1 / TPU_RESULTS.md)."""

    @pytest.mark.slow
    def test_sparse_fleet_cuts_writer_ingress(self, tmp_path,
                                              monkeypatch):
        import dataclasses
        import os

        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        monkeypatch.setenv("BFLC_PROC_TRACE", "1")
        cfg = ProtocolConfig().validate()
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr, ytr, cfg.client_num)
        factory_kw = {"input_shape": (5,), "hidden": 1024,
                      "num_classes": 2}

        def leg(density):
            res = run_federated_processes(
                "make_mlp", shards, (xte, yte),
                dataclasses.replace(cfg, delta_density=density),
                rounds=2, factory_kw=factory_kw,
                wal_path=os.path.join(str(tmp_path),
                                      f"w{density:g}.wal"),
                timeout_s=240)
            assert res.rounds_completed >= 1
            costs = ((res.final_info or {}).get("perf")
                     or {}).get("costs", {})
            return float(costs.get("wire.bytes_in", 0.0)), res

        sparse_in, sres = leg(0.01)
        dense_in, dres = leg(1.0)
        assert sparse_in and dense_in
        # writer ingress is dominated by upload blobs: sparse must cut
        # it hard (>= 3x leaves slack for frames/acks; the benchmark
        # measures the >= 20x EGRESS story at full geometry)
        assert dense_in / sparse_in >= 3.0, (dense_in, sparse_in)
        # and the sparse fleet still learns
        assert sres.best_accuracy() >= 0.5
