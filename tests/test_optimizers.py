"""Local-optimizer flexibility: any optax transform drives the client's
local steps while the protocol wire format (delta = (W0 - W_final)/lr, the
FedAvg-of-models identity) is optimizer-agnostic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

optax = pytest.importorskip("optax")   # optional dependency ('full' extra)

from bflc_demo_tpu.core import local_train, evaluate
from bflc_demo_tpu.client import run_federated
from bflc_demo_tpu.data import load_occupancy, iid_shards
from bflc_demo_tpu.models import make_softmax_regression, make_mlp
from bflc_demo_tpu.protocol import ProtocolConfig

MODEL = make_softmax_regression()


def test_none_matches_plain_sgd():
    """optimizer=None must be byte-equivalent to optax.sgd(lr)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((200, 5)), jnp.float32)
    y = jnp.asarray(np.eye(2, dtype=np.float32)[rng.integers(0, 2, 200)])
    p = MODEL.init_params(0)
    d_none, c_none = local_train(MODEL.apply, p, x, y, lr=0.01,
                                 batch_size=100)
    d_sgd, c_sgd = local_train(MODEL.apply, p, x, y, lr=0.01,
                               batch_size=100, optimizer=optax.sgd(0.01))
    np.testing.assert_allclose(d_none["W"], d_sgd["W"], rtol=1e-6)
    np.testing.assert_allclose(float(c_none), float(c_sgd), rtol=1e-6)


def test_delta_encodes_final_model_for_any_optimizer():
    """delta == (params_in - params_out)/lr regardless of the optimizer, so
    candidate reconstruction (global - lr*delta) recovers the exact local
    model the committee must score."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((200, 5)), jnp.float32)
    y = jnp.asarray(np.eye(2, dtype=np.float32)[rng.integers(0, 2, 200)])
    p = MODEL.init_params(0)
    for opt in (optax.adam(1e-2), optax.sgd(1e-2, momentum=0.9)):
        delta, _ = local_train(MODEL.apply, p, x, y, lr=0.001,
                               batch_size=100, optimizer=opt)
        reconstructed = jax.tree_util.tree_map(
            lambda g, d: g - 0.001 * d, p, delta)
        # train manually with the same optimizer to get the true final model
        opt_state = opt.init(p)
        q = p
        for b in range(2):
            bx, by = x[b * 100:(b + 1) * 100], y[b * 100:(b + 1) * 100]
            g = jax.grad(lambda w: jnp.mean(-jnp.sum(
                by * jax.nn.log_softmax(MODEL.apply(w, bx)), -1)))(q)
            updates, opt_state = opt.update(g, opt_state, q)
            q = optax.apply_updates(q, updates)
        np.testing.assert_allclose(np.asarray(reconstructed["W"]),
                                   np.asarray(q["W"]), rtol=1e-4, atol=1e-6)


def test_momentum_protocol_run():
    """The full protocol runs with a momentum local optimizer and still
    converges on the reference workload."""
    cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                         needed_update_count=3, learning_rate=0.001,
                         batch_size=50).validate()
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr[:2000], ytr[:2000], cfg.client_num)
    res = run_federated(make_softmax_regression(), shards,
                        (xte[:500], yte[:500]), cfg, rounds=5,
                        local_optimizer=optax.sgd(0.001, momentum=0.9))
    assert res.rounds_completed == 5
    assert res.best_accuracy() > 0.75


def test_mesh_runtime_local_optimizer():
    """local_optimizer drives the MESH round program's per-client steps:
    the TPU-first data plane has the same optimizer flexibility as the host
    sim (momentum run converges; differs from plain SGD; audit green)."""
    from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh

    cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                         needed_update_count=3, learning_rate=0.05,
                         batch_size=16, local_epochs=1)
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr[:1200], ytr[:1200], 8)

    def run(opt):
        return run_federated_mesh(MODEL, shards, (xte[:400], yte[:400]),
                                  cfg, rounds=2, seed=5,
                                  local_optimizer=opt)

    plain = run(None)
    mom = run(optax.sgd(0.05, momentum=0.9))
    assert mom.rounds_completed == 2
    assert all(np.isfinite(a) for _, a in mom.accuracy_history)
    assert mom.best_accuracy() > 0.5
    # momentum actually changed the local trajectories
    assert not np.allclose(np.asarray(mom.final_params["W"]),
                           np.asarray(plain.final_params["W"]))


def test_mesh_runtime_optimizer_rejects_batched():
    from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh

    cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                         needed_update_count=3, learning_rate=0.05,
                         batch_size=16, local_epochs=1)
    xtr, ytr, xte, yte = load_occupancy()
    with pytest.raises(ValueError):
        run_federated_mesh(MODEL, iid_shards(xtr[:800], ytr[:800], 8),
                           (xte[:200], yte[:200]), cfg, rounds=4,
                           rounds_per_dispatch=2,
                           local_optimizer=optax.sgd(0.05, momentum=0.9))
