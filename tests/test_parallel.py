"""Sharded data-plane tests on the virtual 8-device CPU mesh.

The invariant under test: the SPMD round (shard_map + ppermute ring + psum)
computes bit-for-bit the same decision and numerically the same model as the
single-device `core` path — distribution must be a pure implementation detail.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.core import (local_train, score_candidates, aggregate,
                                apply_selection, median_scores,
                                rank_desc_stable)
from bflc_demo_tpu.models import make_softmax_regression
from bflc_demo_tpu.parallel import (make_mesh, client_axis_mesh,
                                    sharded_fedavg, sharded_protocol_round)
from bflc_demo_tpu.parallel.mesh import divide_clients

MODEL = make_softmax_regression()


def _client_batch(rng, n_clients, shard, feat=5, classes=2):
    xs = rng.standard_normal((n_clients, shard, feat)).astype(np.float32)
    labels = rng.integers(0, classes, (n_clients, shard))
    ys = np.eye(classes, dtype=np.float32)[labels]
    return jnp.asarray(xs), jnp.asarray(ys)


def test_mesh_helpers():
    assert len(jax.devices()) == 8, "conftest should provide 8 CPU devices"
    mesh = client_axis_mesh(4)
    assert mesh.shape["clients"] == 4
    assert divide_clients(20, mesh) == (5, 4)
    with pytest.raises(ValueError):
        divide_clients(21, mesh)
    mesh2 = make_mesh((2, 4), ("dp", "tp"))
    assert mesh2.shape == {"dp": 2, "tp": 4}


def test_sharded_fedavg_matches_apply_selection():
    rng = np.random.default_rng(0)
    mesh = client_axis_mesh(8)
    n = 16
    params = MODEL.init_params(1)
    deltas = {
        "W": jnp.asarray(rng.standard_normal((n, 5, 2)), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n, 2)), jnp.float32)}
    ns = jnp.asarray(rng.integers(100, 400, n), jnp.int32)
    sel = jnp.asarray(rng.random(n) < 0.5)
    got = sharded_fedavg(mesh, deltas, ns, sel, params, 0.001)
    want = apply_selection(params, deltas, ns, sel, 0.001)
    # psum reduces in tree order, the single-device sum sequentially — allow
    # for float32 reassociation on near-zero elements
    np.testing.assert_allclose(got["W"], want["W"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got["b"], want["b"], rtol=1e-5, atol=1e-6)


class TestShardedProtocolRound:
    def _run(self, n_clients=16, n_dev=8, shard=120, bs=40, k=6, seed=3,
             scoring="committee"):
        rng = np.random.default_rng(seed)
        mesh = client_axis_mesh(n_dev)
        xs, ys = _client_batch(rng, n_clients, shard)
        ns = jnp.full((n_clients,), shard, jnp.int32)
        uploader = jnp.asarray([True] * 10 + [False] * (n_clients - 10))
        committee = jnp.asarray(
            [False] * 10 + [True] * 4 + [False] * (n_clients - 14))
        res = sharded_protocol_round(
            mesh, MODEL.apply, MODEL.init_params(0), xs, ys, ns,
            uploader, committee, lr=0.01, batch_size=bs, local_epochs=1,
            aggregate_count=k, scoring=scoring)
        return rng, xs, ys, ns, uploader, committee, res

    def test_matches_single_device_semantics(self):
        # the dense-oracle path: the ring scores every (scorer, candidate)
        # pair, so the whole matrix is comparable against the host loop
        _, xs, ys, ns, uploader, committee, res = self._run(scoring="ring")
        params = MODEL.init_params(0)
        # reference: per-client local_train + score loop + core.aggregate
        deltas, costs = [], []
        for i in range(xs.shape[0]):
            d, c = local_train(MODEL.apply, params, xs[i], ys[i],
                               lr=0.01, batch_size=40)
            deltas.append(d)
            costs.append(float(c))
        stacked = jax.tree_util.tree_map(lambda *t: jnp.stack(t), *deltas)
        rows = []
        for i in range(xs.shape[0]):
            rows.append(score_candidates(MODEL.apply, params, stacked, 0.01,
                                         xs[i], ys[i]))
        want_matrix = jnp.stack(rows)
        np.testing.assert_allclose(res.score_matrix, want_matrix,
                                   rtol=0, atol=1e-6)
        np.testing.assert_allclose(res.avg_costs, np.asarray(costs),
                                   rtol=1e-6)
        want = aggregate(params, stacked, ns, jnp.asarray(costs),
                         want_matrix, committee, uploader, 0.01, 6)
        np.testing.assert_allclose(res.medians, want.medians, atol=1e-6)
        np.testing.assert_array_equal(res.selected, want.selected)
        np.testing.assert_array_equal(res.order, want.order)
        np.testing.assert_allclose(res.params["W"], want.params["W"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(res.global_loss, want.global_loss,
                                   rtol=1e-5)

    def test_committee_rows_only(self):
        """Medians must depend only on committee rows of the matrix."""
        _, xs, ys, ns, uploader, committee, res = self._run()
        sub = res.score_matrix[np.asarray(committee)]
        med = np.sort(np.asarray(sub), axis=0)
        want = 0.5 * (med[1] + med[2])          # 4 rows -> mean of middle two
        np.testing.assert_allclose(res.medians, want, atol=1e-6)

    def test_selection_respects_uploader_mask(self):
        _, _, _, _, uploader, _, res = self._run()
        assert not np.any(np.asarray(res.selected)[~np.asarray(uploader)])
        assert np.asarray(res.selected).sum() == 6

    def test_mesh_size_invariance(self):
        """Same round on 2-device and 8-device meshes -> same outputs (the
        distribution is semantically invisible)."""
        rng = np.random.default_rng(9)
        xs, ys = _client_batch(rng, 16, 80)
        ns = jnp.full((16,), 80, jnp.int32)
        uploader = jnp.asarray([True] * 12 + [False] * 4)
        committee = jnp.asarray([False] * 12 + [True] * 4)
        outs = []
        for nd in (2, 8):
            res = sharded_protocol_round(
                client_axis_mesh(nd), MODEL.apply, MODEL.init_params(0),
                xs, ys, ns, uploader, committee, lr=0.01, batch_size=40,
                local_epochs=1, aggregate_count=6)
            outs.append(res)
        np.testing.assert_allclose(outs[0].score_matrix, outs[1].score_matrix,
                                   atol=1e-6)
        np.testing.assert_array_equal(outs[0].selected, outs[1].selected)
        np.testing.assert_allclose(outs[0].params["W"], outs[1].params["W"],
                                   rtol=1e-5, atol=1e-6)


class TestCommitteeScoring:
    """The C×K scoring schedule (reference main.py:212-217: only committee
    members score, only the K uploads get scored) against the dense ring."""

    def _round(self, scoring, n_clients=16, n_dev=8, seed=3):
        rng = np.random.default_rng(seed)
        mesh = client_axis_mesh(n_dev)
        xs, ys = _client_batch(rng, n_clients, 120)
        ns = jnp.full((n_clients,), 120, jnp.int32)
        uploader = jnp.asarray([True] * 10 + [False] * (n_clients - 10))
        committee = jnp.asarray(
            [False] * 10 + [True] * 4 + [False] * (n_clients - 14))
        res = sharded_protocol_round(
            mesh, MODEL.apply, MODEL.init_params(0), xs, ys, ns,
            uploader, committee, lr=0.01, batch_size=40, local_epochs=1,
            aggregate_count=6, scoring=scoring)
        return uploader, committee, res

    def test_decision_equivalent_to_ring(self):
        """Same round under both schedules: identical selection, order,
        medians at uploader slots, model, and identical score values on the
        (committee row, uploader column) region both schedules compute."""
        up, cm, ring = self._round("ring")
        _, _, comm = self._round("committee")
        np.testing.assert_array_equal(ring.selected, comm.selected)
        np.testing.assert_array_equal(ring.order, comm.order)
        upm = np.asarray(up)
        np.testing.assert_allclose(np.asarray(ring.medians)[upm],
                                   np.asarray(comm.medians)[upm], atol=1e-6)
        np.testing.assert_allclose(ring.params["W"], comm.params["W"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(ring.global_loss, comm.global_loss,
                                   rtol=1e-6)
        region = np.ix_(np.flatnonzero(np.asarray(cm)),
                        np.flatnonzero(upm))
        np.testing.assert_allclose(np.asarray(ring.score_matrix)[region],
                                   np.asarray(comm.score_matrix)[region],
                                   atol=1e-6)

    def test_sparse_outside_scored_region(self):
        """Committee-path matrix is exactly zero outside committee rows x
        uploader columns (nothing else was evaluated — that IS the saving)."""
        up, cm, res = self._round("committee")
        m = np.asarray(res.score_matrix).copy()
        m[np.ix_(np.flatnonzero(np.asarray(cm)),
                 np.flatnonzero(np.asarray(up)))] = 0.0
        assert np.all(m == 0.0)

    def test_scoring_flops_scale_with_committee_not_clients(self):
        """XLA cost analysis on scoring-only programs: the ring burns
        ~N×N evaluations, the committee schedule ~max(C, n_dev)×K — the
        FLOP ratio must reflect it (VERDICT r3 item 3's 'Done' criterion).

        Uses a model big enough (MLP, ~26k params) that candidate-eval
        FLOPs dominate the committee path's gather/scatter bookkeeping —
        on the 10-parameter softmax model the bookkeeping is the bigger
        term and the ratio says nothing about eval scheduling."""
        from bflc_demo_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P
        from bflc_demo_tpu.eval.mfu import cost_analysis_flops
        from bflc_demo_tpu.models import make_mlp
        from bflc_demo_tpu.parallel.fedavg import (
            AXIS, committee_score_matrix, ring_score_matrix)

        n_dev, k_up, c = 4, 10, 4
        model = make_mlp(input_shape=(64,), hidden=128, num_classes=2)
        mesh = client_axis_mesh(n_dev)
        params = model.init_params(0)

        def flops(scoring, n_clients):
            rng = np.random.default_rng(0)
            xs = jnp.asarray(rng.standard_normal(
                (n_clients, 120, 64)).astype(np.float32))
            ys = jnp.asarray(np.eye(2, dtype=np.float32)[
                rng.integers(0, 2, (n_clients, 120))])
            deltas = jax.tree_util.tree_map(
                lambda l: jnp.asarray(rng.standard_normal(
                    (n_clients,) + l.shape).astype(np.float32)), params)
            up = jnp.asarray([True] * k_up + [False] * (n_clients - k_up))
            cm = jnp.asarray([False] * k_up + [True] * c
                             + [False] * (n_clients - k_up - c))

            def body(p, d, x, y, upm, cmm):
                if scoring == "ring":
                    rows = ring_score_matrix(model.apply, p, d, 0.01, x, y,
                                             n_dev)
                    return jax.lax.all_gather(rows, AXIS, tiled=True)
                return committee_score_matrix(model.apply, p, d, 0.01, x, y,
                                              n_dev, cmm, upm, c, k_up)
            fn = shard_map(
                body, mesh=mesh,
                in_specs=(P(), P("clients"), P("clients"), P("clients"),
                          P(), P()),
                out_specs=P(), check_vma=False)
            compiled = jax.jit(fn).lower(params, deltas, xs, ys, up,
                                         cm).compile()
            return cost_analysis_flops(compiled)

        # Caveat on absolute numbers: XLA's cost analysis counts a
        # fori_loop body ONCE (trip counts are opaque to it), so the ring
        # program's reported flops are one hop's worth — multiply by n_dev
        # for the true total.  The N-scaling comparison below is immune to
        # that: it compares like against like at two client counts.
        r16, r32 = flops("ring", 16), flops("ring", 32)
        c16, c32 = flops("committee", 16), flops("committee", 32)
        # ring: clients/device doubles -> per-hop evals quadruple
        assert r32 > 2.5 * r16, (r16, r32)
        # committee: still c_pad x K evals — N-invariant up to gather cost
        assert c32 < 1.5 * c16, (c16, c32)
        # and the true totals at N=16: ring = n_dev hops x r16 vs c16
        assert c16 < (n_dev * r16) / 3, (n_dev * r16, c16)


class TestRoundBuilderValidation:
    """Static-geometry guards on make_sharded_protocol_round (round-4
    post-mortem: a silent-wrong or hard-raise geometry must fail loudly at
    BUILD time or be caught at CALL time, never score the wrong clients)."""

    def _build(self, **kw):
        from bflc_demo_tpu.parallel.fedavg import make_sharded_protocol_round
        mesh = client_axis_mesh(4)
        base = dict(client_num=8, lr=0.01, batch_size=20, local_epochs=1,
                    aggregate_count=2)
        base.update(kw)
        return make_sharded_protocol_round(mesh, MODEL.apply, **base)

    def test_auto_without_counts_falls_back_to_ring(self):
        """The external-driver contract: no static counts still builds a
        working (ring) program — the exact call shape that broke r4."""
        rng = np.random.default_rng(0)
        xs, ys = _client_batch(rng, 8, 40)
        ns = jnp.full((8,), 40, jnp.int32)
        up = jnp.asarray([True] * 4 + [False] * 4)
        cm = jnp.asarray([False] * 6 + [True] * 2)
        res = self._build()(MODEL.init_params(0), xs, ys, ns, up, cm)
        assert res.score_matrix.shape == (8, 8)
        # dense matrix == ring schedule ran (committee would zero non-
        # committee rows); row 0 is a non-committee scorer
        assert np.any(np.asarray(res.score_matrix)[0] != 0.0)

    def test_auto_half_specified_raises(self):
        with pytest.raises(ValueError, match="half-specified"):
            self._build(comm_count=2)
        with pytest.raises(ValueError, match="half-specified"):
            self._build(needed_update_count=4)

    def test_committee_without_counts_raises(self):
        with pytest.raises(ValueError, match="needs static"):
            self._build(scoring="committee")

    def test_counts_out_of_range_raise(self):
        for bad in (dict(comm_count=-1, needed_update_count=4),
                    dict(comm_count=2, needed_update_count=9),
                    dict(comm_count=9, needed_update_count=4)):
            with pytest.raises(ValueError, match="must be in"):
                self._build(**bad)

    def test_wrong_mask_popcount_rejected_at_call(self):
        """A concrete mask whose popcount disagrees with the static C/K
        would make _first_k_indices score never-uploaded deltas (ADVICE r4
        low) — the wrapper must reject it before dispatch."""
        fn = self._build(comm_count=2, needed_update_count=4)
        rng = np.random.default_rng(0)
        xs, ys = _client_batch(rng, 8, 40)
        ns = jnp.full((8,), 40, jnp.int32)
        up3 = jnp.asarray([True] * 3 + [False] * 5)       # 3 != K=4
        cm = jnp.asarray([False] * 6 + [True] * 2)
        with pytest.raises(ValueError, match="uploader_mask has 3"):
            fn(MODEL.init_params(0), xs, ys, ns, up3, cm)

    def test_multi_round_rejects_trainer_starvation(self):
        """client_num - comm_count < K: the uploader draw (which excludes
        committee members) could never yield K uploaders."""
        from bflc_demo_tpu.parallel.fedavg import make_multi_round_program
        mesh = client_axis_mesh(4)
        with pytest.raises(ValueError, match="excludes committee"):
            make_multi_round_program(
                mesh, MODEL.apply, client_num=8, lr=0.01, batch_size=20,
                local_epochs=1, aggregate_count=2, comm_count=4,
                needed_update_count=6, rounds_per_dispatch=2)
