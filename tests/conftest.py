"""Test env: force JAX onto CPU with 8 virtual devices before backend init.

Multi-chip hardware is not available in CI; all sharding/collective tests run
on a virtual 8-device CPU mesh (the same mechanism the driver uses for the
multichip dryrun).  This mirrors the reference's own answer to "test
distributed behavior on one box": loopback multi-process with real identities
(SURVEY.md §4) — here, loopback multi-device with real shardings.

Env vars take effect at XLA backend creation, not jax import, so this works
even though some pytest plugins import jax early; we additionally poke
jax.config when jax is already in sys.modules.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

if "jax" in sys.modules:
    import jax
    assert not jax._src.xla_bridge._backends, (
        "XLA backend initialised before conftest could set "
        "JAX_PLATFORMS/XLA_FLAGS; run pytest from the repo root")
    jax.config.update("jax_platforms", "cpu")

# Tests must see the seeded synthetic distributions the convergence bars
# were calibrated against — never real .npz files leaked in from the host
# environment (data/synthetic._real_or_synthetic keys off this var).
os.environ.pop("BFLC_DATA_DIR", None)
