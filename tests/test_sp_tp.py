"""Composed sp x tp: ring attention with head-sharded QKV must compute the
same function (and gradients) as the single-device forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.models.transformer import (
    make_transformer_classifier, transformer_forward)
from bflc_demo_tpu.parallel.mesh import make_mesh
from bflc_demo_tpu.parallel.ring_attention import SP_AXIS
from bflc_demo_tpu.parallel.sp_tp import (make_sp_tp_transformer_forward,
                                          TP_AXIS)


def _model(seq_len=32, heads=4):
    return make_transformer_classifier(vocab_size=100, seq_len=seq_len,
                                       num_classes=3, dim=32, depth=2,
                                       heads=heads)


def _tokens(rng, b, s):
    x = rng.integers(1, 100, (b, s)).astype(np.int32)
    lengths = rng.integers(s // 2, s + 1, b)
    for i in range(b):
        x[i, lengths[i]:] = 0
    return jnp.asarray(x)


class TestSpTpForward:
    @pytest.mark.parametrize("n_sp,n_tp", [(2, 2), (4, 2), (2, 4)])
    def test_matches_single_device(self, n_sp, n_tp):
        model = _model()
        cfg = model.config
        mesh = make_mesh((n_sp, n_tp), (SP_AXIS, TP_AXIS))
        rng = np.random.default_rng(0)
        tokens = _tokens(rng, 4, cfg.seq_len)
        params = model.init_params(0)
        want = transformer_forward(params, tokens, cfg)
        got = make_sp_tp_transformer_forward(mesh, cfg)(params, tokens)
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=2e-5)

    def test_heavy_padding(self):
        """Sequence shards that are 100% PAD must stay inert through the
        ring even when each device only holds a head slice."""
        model = _model()
        cfg = model.config
        mesh = make_mesh((4, 2), (SP_AXIS, TP_AXIS))
        rng = np.random.default_rng(1)
        tokens = np.array(rng.integers(1, 100, (3, 32)), np.int32)
        tokens[:, 6:] = 0               # only 1 of 4 sp shards has real keys
        tokens = jnp.asarray(tokens)
        want = transformer_forward(params := model.init_params(0), tokens,
                                   cfg)
        got = make_sp_tp_transformer_forward(mesh, cfg)(params, tokens)
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=2e-5)
        assert np.all(np.isfinite(np.asarray(got)))

    def test_gradients_match(self):
        """Training through the composed mesh: autodiff through the ring +
        both psum families must reproduce single-device gradients."""
        model = _model()
        cfg = model.config
        mesh = make_mesh((2, 2), (SP_AXIS, TP_AXIS))
        rng = np.random.default_rng(2)
        tokens = _tokens(rng, 4, cfg.seq_len)
        labels = jax.nn.one_hot(jnp.asarray(rng.integers(0, 3, 4)), 3)
        params = model.init_params(0)
        sp_tp_fn = make_sp_tp_transformer_forward(mesh, cfg)

        def loss_via(fwd):
            def f(p):
                logits = fwd(p, tokens)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32))
                return -jnp.mean(jnp.sum(labels * logp, -1))
            return f

        g_want = jax.grad(loss_via(
            lambda p, t: transformer_forward(p, t, cfg)))(params)
        g_got = jax.grad(loss_via(sp_tp_fn))(params)
        flat_w, _ = jax.tree_util.tree_flatten(g_want)
        flat_g, _ = jax.tree_util.tree_flatten(g_got)
        for w, g in zip(flat_w, flat_g):
            np.testing.assert_allclose(g, w, rtol=2e-3, atol=1e-5)

    def test_rejects_bad_geometry(self):
        model = _model(heads=4)
        mesh = make_mesh((1, 8), (SP_AXIS, TP_AXIS))
        with pytest.raises(ValueError, match="heads"):
            make_sp_tp_transformer_forward(mesh, model.config)


class TestSpTpPallasRing:
    def test_sp_tp_with_flash_ring_matches_einsum(self):
        """sp x tp with the flash-kernel ring hops (attention_impl) — the
        three-way composition: heads sharded over tp, sequence over sp,
        KV tiles streamed within the chip."""
        from bflc_demo_tpu.models.transformer import (
            make_transformer_classifier, transformer_forward)
        model = make_transformer_classifier(vocab_size=100, seq_len=32,
                                            num_classes=3, dim=32, depth=1,
                                            heads=2)
        kernel_cfg = make_transformer_classifier(
            vocab_size=100, seq_len=32, num_classes=3, dim=32, depth=1,
            heads=2, attention_impl="pallas_interpret").config
        mesh = make_mesh((2, 2), (SP_AXIS, TP_AXIS))
        rng = np.random.default_rng(31)
        tokens = _tokens(rng, 3, 32)
        params = model.init_params(0)
        want = transformer_forward(params, tokens, model.config)
        got = make_sp_tp_transformer_forward(mesh, kernel_cfg)(params,
                                                              tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-4, atol=5e-5)


class TestSPTPTrainStep:
    """Composed long-context training: grads through the ring AND the
    per-sublayer tp reductions must equal the single-device step.  Head
    randomized — zero-init head makes body grads zero and the check
    vacuous (the round-5 sp-training post-mortem)."""

    @pytest.mark.parametrize("n_sp,n_tp", [(2, 2), (4, 2), (2, 4)])
    def test_matches_single_device_step(self, n_sp, n_tp):
        from bflc_demo_tpu.models.transformer import transformer_forward
        from bflc_demo_tpu.parallel.sp_tp import make_sp_tp_train_step
        model = _model()
        cfg = model.config
        mesh = make_mesh((n_sp, n_tp), (SP_AXIS, TP_AXIS))
        rng = np.random.default_rng(9)
        tokens = _tokens(rng, 4, cfg.seq_len)
        labels = jnp.asarray(np.eye(cfg.num_classes, dtype=np.float32)[
            rng.integers(0, cfg.num_classes, 4)])
        params = model.init_params(9)
        params["head_w"] = jax.random.normal(
            jax.random.PRNGKey(9), params["head_w"].shape,
            jnp.float32) * 0.5

        def loss_fn(p):
            logits = transformer_forward(p, tokens, cfg)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(jnp.sum(labels * logp, axis=-1))

        want_l, g = jax.value_and_grad(loss_fn)(params)
        want_p = jax.tree_util.tree_map(
            lambda w, d: w - 0.1 * d, params, g)
        # non-vacuity: the body moved
        assert float(jnp.abs(want_p["blocks"][0]["w1"]
                             - params["blocks"][0]["w1"]).max()) > 1e-6

        step = make_sp_tp_train_step(mesh, cfg, lr=0.1)
        got_p, got_l = step(params, tokens, labels)
        np.testing.assert_allclose(float(got_l), float(want_l), rtol=2e-5)
        for (path, w), gg in zip(
                jax.tree_util.tree_flatten_with_path(want_p)[0],
                jax.tree_util.tree_leaves(got_p)):
            np.testing.assert_allclose(
                np.asarray(gg), np.asarray(w), rtol=5e-4, atol=5e-5,
                err_msg=jax.tree_util.keystr(path))
