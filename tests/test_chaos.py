"""Chaos engine: seeded fault campaigns against the real process
federation, with continuous invariant checking (bflc_demo_tpu.chaos).

Three layers:
- unit: FaultSchedule determinism/replayability from one integer seed,
  wire-spec concretization, FaultInjector semantics at the frame
  boundary, torn-WAL injection + recovery;
- the tier-1 MINI-SOAK: a fixed, fully deterministic campaign (kill +
  partition + validator kill/restart + writer kill) over a small fleet —
  every invariant monitor must hold and the federation must finish;
- the 100-round soak (slow): the headline campaign at config-1 parity
  geometry (20 clients + 2 standbys + 4 validators + quorum), randomized
  from a seed, reaching reference-level accuracy under fire
  (tools/chaos_soak.py is the CLI twin that emits the JSON artifact).
"""

import os
import time

import numpy as np
import pytest

from bflc_demo_tpu.chaos.hooks import FaultInjector, tear_wal_tail
from bflc_demo_tpu.chaos.schedule import (FaultEvent, FaultSchedule,
                                          PROFILES, WireWindow)
from bflc_demo_tpu.comm.wire import WireError
from bflc_demo_tpu.data import load_occupancy, iid_shards
from bflc_demo_tpu.data.occupancy import occupancy_source
from bflc_demo_tpu.ledger.pyledger import PyLedger
from bflc_demo_tpu.protocol.constants import ProtocolConfig


class TestFaultSchedule:
    def test_replayable_from_one_seed(self):
        kw = dict(duration_s=300.0, n_clients=20, n_standbys=2,
                  n_validators=4, profile="standard")
        a, b = FaultSchedule(1234, **kw), FaultSchedule(1234, **kw)
        assert a.summary() == b.summary()
        assert [w.as_dict() for r in sorted(a.wire_windows)
                for w in a.wire_windows[r]] == \
               [w.as_dict() for r in sorted(b.wire_windows)
                for w in b.wire_windows[r]]
        c = FaultSchedule(1235, **kw)
        assert c.summary() != a.summary()   # the seed IS the campaign

    def test_profiles_and_structure(self):
        assert set(PROFILES) == {"light", "standard", "heavy",
                                 "heavytail", "churn"}
        with pytest.raises(ValueError):
            FaultSchedule(1, duration_s=60, n_clients=4, n_standbys=1,
                          n_validators=4, profile="nope")
        s = FaultSchedule(7, duration_s=600.0, n_clients=20,
                          n_standbys=2, n_validators=4)
        ts = [e.t for e in s.events]
        assert ts == sorted(ts)
        assert all(e.t >= s.grace_s for e in s.events)
        # every kill of a restartable role has a matching restart; writer
        # kills never restart (fencing) and never exceed the standby count
        kills = [e for e in s.events if e.kind == "kill"]
        writer_kills = [e for e in kills if e.target == "writer"]
        assert 0 < len(writer_kills) <= 2
        for e in kills:
            if e.target == "writer":
                continue
            assert any(r.kind == "restart" and r.target == e.target
                       and r.t > e.t for r in s.events), e

    def test_wire_spec_concretizes_ports(self):
        s = FaultSchedule(7, duration_s=120.0, n_clients=4, n_standbys=1,
                          n_validators=4)
        s.wire_windows = {"client-0": [WireWindow(
            5.0, 9.0, "partition", ("writer", "standby-1"))]}
        spec = s.wire_spec("client-0", 1000.0,
                           {"writer": 7001, "standby-1": 7002})
        assert spec["t0"] == 1000.0 and spec["role"] == "client-0"
        assert spec["windows"][0]["ports"] == [7001, 7002]
        assert s.wire_spec("client-1", 1000.0, {}) is None


class _FakeSock:
    def __init__(self, port):
        self._port = port

    def getpeername(self):
        return ("127.0.0.1", self._port)


class TestFaultInjector:
    def _spec(self, windows):
        return {"t0": time.time(), "role": "client-0", "seed": 1,
                "windows": windows}

    def test_partition_blocks_only_listed_ports_in_window(self):
        inj = FaultInjector(self._spec([
            {"start": -1.0, "end": 60.0, "mode": "partition",
             "ports": [7001], "p": 1.0, "delay_ms": 0.0}]))
        with pytest.raises(WireError):
            inj.on_send(_FakeSock(7001))
        inj.on_send(_FakeSock(7002))            # other peers untouched
        inj.on_recv(_FakeSock(7002))
        assert inj.injected["partition"] == 1

    def test_window_expiry_and_drop_and_delay(self):
        inj = FaultInjector(self._spec([
            {"start": -10.0, "end": -5.0, "mode": "partition",
             "ports": [], "p": 1.0, "delay_ms": 0.0}]))
        inj.on_send(_FakeSock(7001))            # expired window: clean
        drop = FaultInjector(self._spec([
            {"start": -1.0, "end": 60.0, "mode": "drop", "ports": [],
             "p": 1.0, "delay_ms": 0.0}]))
        with pytest.raises(WireError):
            drop.on_recv(_FakeSock(7001))
        slow = FaultInjector(self._spec([
            {"start": -1.0, "end": 60.0, "mode": "delay", "ports": [],
             "p": 1.0, "delay_ms": 30.0}]))
        t0 = time.monotonic()
        slow.on_send(_FakeSock(7001))
        assert time.monotonic() - t0 >= 0.025
        assert slow.injected["delay"] == 1


class TestTornWAL:
    def test_torn_tail_recovers_to_intact_prefix(self, tmp_path):
        cfg = ProtocolConfig(client_num=4, comm_count=2,
                             aggregate_count=2, needed_update_count=2)
        path = str(tmp_path / "chain.wal")
        led = PyLedger(4, 2, 2, 2)
        assert led.attach_wal(path)
        for i in range(4):
            led.register_node(f"0x{i:040x}")
        led.detach_wal()
        assert tear_wal_tail(path, nbytes=5)
        fresh = PyLedger(4, 2, 2, 2)
        # the torn final record is skipped; the intact prefix replays
        assert fresh.replay_wal(path) == 3
        assert fresh.num_registered == 3
        assert cfg  # geometry documented above

    def test_tear_refuses_tiny_files(self, tmp_path):
        p = tmp_path / "tiny.wal"
        p.write_bytes(b"BFLCWAL1")
        assert not tear_wal_tail(str(p))


def _small_cfg():
    return ProtocolConfig(client_num=4, comm_count=2, aggregate_count=2,
                          needed_update_count=2, learning_rate=0.05,
                          batch_size=32, local_epochs=2).validate()


def _occupancy_fleet(n):
    xtr, ytr, xte, yte = load_occupancy()
    return (iid_shards(np.asarray(xtr), np.asarray(ytr), n),
            (np.asarray(xte), np.asarray(yte)))


class TestMiniSoak:
    """The tier-1 chaos drill: a fixed deterministic campaign composing
    client kill/restart, validator kill/restart (certified-backlog
    resync on rejoin), a writer<->validator partition window, a lossy
    client link, and a writer kill (BFT-certified promotion) — all
    invariant monitors must hold and the federation must finish.  Runs
    with the telemetry plane armed (PR 4): the same drill must leave a
    chaos-correlated metrics.jsonl timeline and flight-recorder dumps
    from the KILLED processes."""

    def test_seeded_mini_soak_kill_partition_resync(self, tmp_path):
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        cfg = _small_cfg()
        shards, test_set = _occupancy_fleet(cfg.client_num)
        sched = FaultSchedule(123, duration_s=90.0, n_clients=4,
                              n_standbys=1, n_validators=4,
                              profile="light")
        # a handcrafted, fully deterministic event list (same object
        # shape the seed generator emits — the generator is drilled
        # above; here the COMPOSITION is pinned so the drill always
        # exercises kill + partition + resync + failover)
        sched.events = [
            FaultEvent(4.0, "kill", "validator-1"),
            FaultEvent(7.0, "restart", "validator-1"),
            FaultEvent(9.0, "kill", "client-2"),
            FaultEvent(11.0, "restart", "client-2"),
            FaultEvent(13.0, "kill", "writer"),
        ]
        sched.wire_windows = {
            "writer": [WireWindow(5.0, 8.0, "partition",
                                  ("validator-2",))],
            "client-1": [WireWindow(6.0, 9.0, "drop",
                                    ("writer", "standby-1"), p=0.3)],
        }
        tdir = str(tmp_path / "telemetry")
        res = run_federated_processes(
            "make_softmax_regression", shards, test_set, cfg,
            rounds=8, standbys=1, bft_validators=4,
            timeout_s=300.0, chaos_schedule=sched,
            telemetry_dir=tdir, verbose=False)
        rep = res.chaos_report
        assert rep is not None
        assert rep["violations"] == [], rep["violations"]
        assert res.rounds_completed >= 8
        v = rep["invariant_verdicts"]
        assert v["monotone_progress"] == "PASS"
        assert v["no_uncertified_bind"] == "PASS"
        assert v["single_certified_history"] == "PASS"
        assert v["acked_upload_durability"] == "PASS"
        executed = {(e["kind"], e["target"])
                    for e in rep["faults_executed"]}
        assert ("kill", "validator-1") in executed
        assert ("restart", "validator-1") in executed
        # the restarted validator rejoined the certified history
        assert int(v["validators_probed"]) >= 3
        assert rep["invariant_checks"]["history_checks"] >= 1
        assert rep["acked_uploads_checked"] >= 1

        # --- telemetry plane under the same faults (PR 4) ---
        import os as _os

        from bflc_demo_tpu.obs.collector import load_timeline
        from bflc_demo_tpu.obs.flight import load_flight
        tel = res.telemetry_report
        assert tel is not None and tel["scrapes"] >= 3
        tl = load_timeline(tel["jsonl"])
        scrapes = [r for r in tl if r["type"] == "scrape"]
        faults = [r for r in tl if r["type"] == "fault"]
        # chaos events landed on the same timeline as the scrapes —
        # the fault -> metric causality stream
        assert any(f.get("kind") == "kill" for f in faults), faults
        # every role CLASS appears in the scraped snapshots
        seen = set()
        for s in scrapes:
            seen |= set(s["roles"])
        assert any(r.startswith("client-") for r in seen)
        assert any(r.startswith("validator-") for r in seen)
        assert any(r.startswith("standby-") for r in seen)
        assert "writer" in seen
        # the KILLED writer's flight-recorder dump exists and parses
        # (SIGKILL — only the periodic out-of-band flush can have
        # written it), and so does the killed validator's
        for role in ("writer", "validator-1"):
            dump = load_flight(_os.path.join(tdir,
                                             f"{role}.flight.jsonl"))
            assert dump["header"]["role"] == role
        # post-writer-kill scrapes degraded, never crashed: the dead
        # writer shows up as a coverage miss in at least one scrape
        assert any("writer" in s["coverage"]["missing"]
                   for s in scrapes), \
            [s["coverage"] for s in scrapes]
        # the prometheus dump rendered
        assert _os.path.exists(tel["prometheus"])


class TestReadFanoutDegradation:
    """Data-plane chaos (PR 5): clients route model/blob reads through
    standby read replicas (comm.dataplane).  Killing EVERY serving
    replica mid-federation must degrade reads to the coordinator
    fallback — rounds keep completing, every invariant holds, and no
    client ever accepts unverified bytes (hash checks make a dead or
    stale replica cost a round-trip, not correctness)."""

    def test_killing_serving_replicas_degrades_to_coordinator(
            self, tmp_path):
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        from bflc_demo_tpu.obs.collector import load_timeline
        cfg = _small_cfg()
        shards, test_set = _occupancy_fleet(cfg.client_num)
        sched = FaultSchedule(321, duration_s=60.0, n_clients=4,
                              n_standbys=2, n_validators=0,
                              profile="light")
        # both read-serving standbys die mid-run; the writer survives,
        # so every later read must fall back to it
        sched.events = [
            FaultEvent(5.0, "kill", "standby-1"),
            FaultEvent(6.5, "kill", "standby-2"),
        ]
        # a modest persistent delay on EVERY client's writer frames
        # (both trainers are needed each round at this 2-of-2
        # geometry) keeps the federation running past the second
        # kill's wall-clock offset even on an idle fast host — without
        # it, a quick fleet finishes all 6 rounds before 6.5 s and the
        # kill is skipped as moot (observed flake)
        sched.wire_windows = {
            f"client-{i}": [WireWindow(0.0, 300.0, "delay",
                                       ("writer",), p=1.0,
                                       delay_ms=120.0)]
            for i in range(4)
        }
        tdir = str(tmp_path / "telemetry")
        res = run_federated_processes(
            "make_softmax_regression", shards, test_set, cfg,
            rounds=6, standbys=2, timeout_s=300.0,
            chaos_schedule=sched, telemetry_dir=tdir, verbose=False)
        rep = res.chaos_report
        assert rep is not None
        assert rep["violations"] == [], rep["violations"]
        assert res.rounds_completed >= 6
        executed = {(e["kind"], e["target"])
                    for e in rep["faults_executed"]}
        assert ("kill", "standby-1") in executed
        assert ("kill", "standby-2") in executed
        # telemetry: clients actually exercised the fallback ladder.
        # Cold-start reads hit the writer too, so "writer reads exist"
        # would pass vacuously — the degradation signal is that writer-
        # sourced reads KEEP GROWING after the last replica kill (the
        # timeline is ordered: scrapes and fault records interleave).
        tl = load_timeline(res.telemetry_report["jsonl"])

        def _writer_reads(rec) -> float:
            total = 0.0
            for role, snap in rec.get("roles", {}).items():
                if not role.startswith("client-"):
                    continue
                for s in ((snap.get("metrics") or {}).get(
                        "dataplane_reads_total") or {}).get(
                            "samples", []):
                    if s["labels"].get("source") == "writer":
                        total += s["value"]     # cumulative per client
            return total

        running, at_last_kill, kills_seen = 0.0, 0.0, 0
        for rec in tl:
            if rec.get("type") == "scrape":
                running = max(running, _writer_reads(rec))
            elif rec.get("type") == "fault" and \
                    rec.get("kind") == "kill":
                kills_seen += 1
                at_last_kill = running
        assert kills_seen >= 2, "kill faults missing from the timeline"
        assert running > at_last_kill, \
            ("no coordinator-fallback reads AFTER the replica kills "
             f"(cumulative writer reads {at_last_kill} -> {running})")


class TestCellAggregatorKill:
    """Hierarchical-tier chaos (PR 6): `ChaosCampaign` SIGKILLs a cell
    aggregator mid-federation.  The dead cell's members re-home to the
    ring sibling (FailoverClient endpoint rotation + TOFU re-register)
    and keep contributing; the root round heals through the standard
    stall recovery (close_round / reseat / force_aggregate over the
    surviving cells) — every invariant holds and every member finishes
    its rounds loop, which for an orphaned member is only reachable
    through the sibling."""

    def test_cell_kill_rehomes_members_invariants_hold(self, tmp_path):
        from bflc_demo_tpu.hier.runtime import run_federated_hier
        from bflc_demo_tpu.obs.collector import load_timeline
        cfg = ProtocolConfig(client_num=6, comm_count=2,
                             aggregate_count=2, needed_update_count=2,
                             learning_rate=0.05, batch_size=32,
                             local_epochs=2).validate()
        shards, test_set = _occupancy_fleet(cfg.client_num)
        sched = FaultSchedule(11, duration_s=60.0, n_clients=6,
                              n_standbys=0, n_validators=0,
                              profile="light")
        # one surgical fault, deterministically placed: kill cell-1's
        # aggregator (no restart — the orphaned members must re-home to
        # sibling cell-2 for the rest of the campaign)
        sched.events = [FaultEvent(12.0, "kill", "cell-1")]
        # a modest delay on every member's frames UNTIL the kill keeps
        # the federation running past its wall-clock offset even on a
        # warm fast host — without it, a quick fleet finishes all 3
        # rounds before 12 s and the kill is skipped as moot (the same
        # observed flake TestReadFanoutDegradation fixed this way).
        # The window ends with the kill so the re-home + finish phase
        # runs at full speed (this test must stay under the tier-1
        # per-test ceiling, tools/check_tier1_budget.py)
        sched.wire_windows = {
            f"client-{i}": [WireWindow(0.0, 12.5, "delay", (),
                                       p=1.0, delay_ms=60.0)]
            for i in range(cfg.client_num)
        }
        tdir = str(tmp_path / "telemetry")
        # tighter stall timeouts: after the kill every root round waits
        # out recovery for the dead cell — the default 12 s root stall
        # made the drill pay ~14 s per post-kill round for nothing
        res = run_federated_hier(
            "make_softmax_regression", shards, test_set, cfg,
            rounds=3, cells=3, timeout_s=300.0,
            stall_timeout_s=3.0, root_stall_timeout_s=5.0,
            chaos_schedule=sched, chaos_dir=str(tmp_path / "chaos"),
            telemetry_dir=tdir)
        rep = res.chaos_report
        assert rep is not None
        assert rep["violations"] == [], rep["violations"]
        assert res.rounds_completed >= 3
        v = rep["invariant_verdicts"]
        assert v["monotone_progress"] == "PASS"
        executed = {(e["kind"], e["target"])
                    for e in rep["faults_executed"]}
        assert ("kill", "cell-1") in executed, rep
        # re-home proof: cell-1's members finished their rounds loop
        # cleanly (exit 0) — with their aggregator dead, the only route
        # to the remaining epochs runs through the sibling
        plan = res.cell_plan
        orphans = plan.members[1]
        assert len(orphans) == 2
        for i in orphans:
            assert res.client_exitcodes[i] == 0, \
                (i, res.client_exitcodes)
        # the kill landed on the chaos-correlated telemetry timeline,
        # and the dead aggregator shows up as a scrape coverage miss
        tl = load_timeline(res.telemetry_report["jsonl"])
        faults = [r for r in tl if r.get("type") == "fault"]
        assert any(f.get("kind") == "kill" and f.get("target") == "cell-1"
                   for f in faults), faults
        scrapes = [r for r in tl if r.get("type") == "scrape"]
        assert any("cell-1" in s["coverage"]["missing"]
                   for s in scrapes), \
            [s["coverage"] for s in scrapes]


@pytest.mark.slow
class TestChaosSoak100:
    """The headline artifact: 100 rounds at config-1 parity geometry
    (20 clients + 2 standbys + 4 validators + quorum-ack + WAL) under a
    seeded randomized kill/partition/delay/drop/tear campaign.  All
    invariants hold and the run reaches reference-level accuracy
    (source-aware bar, as in tests/test_e2e.py)."""

    def test_100_round_randomized_campaign(self, tmp_path):
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        cfg = ProtocolConfig().validate()        # config-1 parity genome
        shards, test_set = _occupancy_fleet(cfg.client_num)
        res = run_federated_processes(
            "make_softmax_regression", shards, test_set, cfg,
            rounds=100, standbys=2, quorum=1, bft_validators=4,
            wal_path=str(tmp_path / "writer.wal"),
            timeout_s=2400.0,
            chaos_seed=int(os.environ.get("BFLC_CHAOS_SEED", "7")),
            chaos_profile="standard", chaos_duration_s=300.0,
            verbose=True)
        rep = res.chaos_report
        assert rep is not None
        assert rep["violations"] == [], rep["violations"]
        assert res.rounds_completed >= 100
        v = rep["invariant_verdicts"]
        for key in ("monotone_progress", "no_uncertified_bind",
                    "single_certified_history",
                    "acked_upload_durability"):
            assert v[key] == "PASS", (key, v)
        # real faults actually fired (a quiet campaign proves nothing)
        executed = rep["faults_executed"]
        assert any(e["kind"] == "kill" and e["target"] == "writer"
                   for e in executed), executed
        assert sum(1 for e in executed if e["kind"] == "kill") >= 5
        # reference-level accuracy UNDER FIRE (source-aware bar — the
        # real UCI distribution supports the 0.92 reference plateau, the
        # synthetic stand-in oscillates around a different peak; same
        # convention as tests/test_e2e.py)
        if occupancy_source() == "csv":
            assert res.final_accuracy >= 0.92, res.accuracy_history[-5:]
        else:
            assert res.best_accuracy() >= 0.85, res.accuracy_history[-5:]
            assert res.final_accuracy >= 0.80, res.accuracy_history[-5:]
