"""Data-plane fast path (comm.dataplane): content-addressed blob cache,
replica read fan-out, batched-fetch fallback accounting, and the opt-in
quantized-delta admission/aggregation path.

Trust invariant under test throughout: fan-out, caching and quantization
move BYTES, never trust — every accepted read is verified against a hash
the client already holds (the writer-asserted model hash, the certified
op's payload hash), so a stale, dead or lying replica can only ever cost
a fallback round-trip.
"""

import dataclasses
import hashlib
import threading
import time

import numpy as np
import pytest

from bflc_demo_tpu.comm.dataplane import (BlobCache, ReadFanoutServer,
                                          ReadRouter, handle_read)
from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                               LedgerServer)
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils.serialization import (dequantize_entries,
                                               pack_pytree,
                                               pack_quantized,
                                               unpack_pytree)

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)


def _init_blob():
    return pack_pytree({"W": np.zeros((5, 2), np.float32),
                        "b": np.zeros((2,), np.float32)})


def _delta(v: float):
    return {"W": np.full((5, 2), v, np.float32),
            "b": np.zeros((2,), np.float32)}


class TestBlobCache:
    def test_put_get_and_lru_eviction_under_byte_budget(self):
        c = BlobCache(max_bytes=100)
        c.put("a", b"x" * 40)
        c.put("b", b"y" * 40)
        assert c.get("a") == b"x" * 40      # refresh 'a' (now MRU)
        c.put("c", b"z" * 40)               # over budget: evict LRU = 'b'
        assert c.get("b") is None
        assert c.get("a") is not None and c.get("c") is not None

    def test_oversized_blob_never_flushes_working_set(self):
        c = BlobCache(max_bytes=100)
        c.put("a", b"x" * 50)
        c.put("big", b"z" * 1000)           # bigger than the whole budget
        assert c.get("big") is None
        assert c.get("a") == b"x" * 50

    def test_replacement_updates_byte_accounting(self):
        c = BlobCache(max_bytes=100)
        c.put("a", b"x" * 90)
        c.put("a", b"y" * 10)               # replace, don't double-count
        c.put("b", b"z" * 80)
        assert c.get("a") == b"y" * 10 and c.get("b") == b"z" * 80


class TestHandleRead:
    """The ONE shared read dispatch every serving role answers through."""

    def test_blob_and_blobs_and_model(self):
        store = {hashlib.sha256(b"one").digest(): b"one",
                 hashlib.sha256(b"two").digest(): b"two"}
        model = b"model-bytes"
        mh = hashlib.sha256(model).digest()
        kw = dict(blob_lookup=store.get,
                  model_state=lambda: (3, mh, model),
                  read_set=[("127.0.0.1", 9)])
        h1 = hashlib.sha256(b"one").hexdigest()
        assert handle_read("blob", {"hash": h1}, **kw)["blob"] == b"one"
        r = handle_read("blobs", {"hashes": [h1, "ff" * 32]}, **kw)
        assert r["parts"] == [[h1, 3]] and r["blob"] == b"one"
        meta = handle_read("model", {"meta": 1}, **kw)
        assert meta == {"ok": True, "epoch": 3, "hash": mh.hex(),
                        "read_set": [["127.0.0.1", 9]]}
        full = handle_read("model", {}, **kw)
        assert full["blob"] == model
        assert handle_read("upload", {}, **kw) is None

    def test_unknown_blob_and_missing_model(self):
        kw = dict(blob_lookup=lambda d: None, model_state=lambda: None)
        assert not handle_read("blob", {"hash": "aa" * 32}, **kw)["ok"]
        assert not handle_read("model", {}, **kw)["ok"]


class TestReadFanout:
    def test_replica_serves_hash_verified_reads(self):
        store = {hashlib.sha256(b"abc").digest(): b"abc"}
        model = _init_blob()
        rep = ReadFanoutServer(
            store.get,
            lambda: (0, hashlib.sha256(model).digest(), model))
        rep.start()
        try:
            c = CoordinatorClient(rep.host, rep.port)
            h = hashlib.sha256(b"abc").hexdigest()
            from bflc_demo_tpu.comm.wire import blob_bytes
            assert blob_bytes(
                c.request("blob", hash=h)["blob"]) == b"abc"
            mr = c.request("model")
            assert blob_bytes(mr["blob"]) == model
            # mutations are refused with an error frame, never served
            r = c.request("upload", addr="0x0", blob=b"", hash="",
                          n=1, cost=0.0, epoch=0)
            assert not r["ok"] and "unknown method" in r["error"]
            c.close()
        finally:
            rep.close()

    def test_lying_replica_fails_hash_check_and_router_falls_back(self):
        """A replica serving WRONG bytes for the model is skipped (the
        writer-asserted hash does not match) and the read degrades to
        the coordinator — wrong bytes can never reach the caller."""
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        liar = ReadFanoutServer(
            lambda d: b"not-the-blob",
            lambda: (0, hashlib.sha256(b"forged").digest(), b"forged"))
        liar.start()
        try:
            ctl = CoordinatorClient(srv.host, srv.port)
            router = ReadRouter(ctl)
            router._read_set = [liar.endpoint]
            mr = router.fetch_model()
            assert mr["ok"] and mr["source"] == "writer"
            assert mr["blob"] == _init_blob()
            ctl.close()
        finally:
            liar.close()
            srv.close()

    def test_stale_replica_first_in_rotation_does_not_mask_fresh_one(self):
        """Round-robin failover must sweep ON from a declining replica:
        advancing the rotation pointer mid-sweep used to re-probe the
        stale replica and never reach the fresh one (regression)."""
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        model = _init_blob()
        stale = ReadFanoutServer(
            lambda d: None,
            lambda: (0, hashlib.sha256(b"old-model").digest(),
                     b"old-model"))
        fresh = ReadFanoutServer(
            lambda d: None,
            lambda: (0, hashlib.sha256(model).digest(), model))
        stale.start()
        fresh.start()
        try:
            ctl = CoordinatorClient(srv.host, srv.port)
            router = ReadRouter(ctl)
            router._read_set = [stale.endpoint, fresh.endpoint]
            router._rr = 0              # stale replica probed first
            mr = router.fetch_model()
            assert mr["ok"] and mr["blob"] == model
            assert mr["source"] == "replica", mr["source"]
            ctl.close()
        finally:
            stale.close()
            fresh.close()
            srv.close()

    def test_dead_replica_mid_run_degrades_to_coordinator(self):
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        payload = b"p" * 4096
        digest = hashlib.sha256(payload).digest()
        srv._blobs[digest] = payload
        rep = ReadFanoutServer({digest: payload}.get, lambda: None)
        rep.start()
        try:
            ctl = CoordinatorClient(srv.host, srv.port)
            router = ReadRouter(ctl)
            router._read_set = [rep.endpoint]
            h = digest.hex()
            assert router.fetch_blobs([h])[h] == payload
            # the serving replica dies; the next (uncached) fetch must
            # fall back to the coordinator, not fail
            rep.close()
            payload2 = b"q" * 4096
            d2 = hashlib.sha256(payload2).digest()
            srv._blobs[d2] = payload2
            assert router.fetch_blobs([d2.hex()])[d2.hex()] == payload2
            ctl.close()
        finally:
            rep.close()
            srv.close()


class _StubControl:
    """Duck-typed control client whose batched `blobs` reply OMITS some
    hashes (a lagging or buggy peer) — the per-hash fallback fixture."""

    def __init__(self, store):
        self.store = store              # hex -> bytes
        self.calls = []

    def request(self, method, **fields):
        self.calls.append(method)
        if method == "blobs":
            served = {h: self.store[h]
                      for h in fields["hashes"][:1] if h in self.store}
            return {"ok": True,
                    "parts": [[h, len(b)] for h, b in served.items()],
                    "blob": b"".join(served.values())}
        if method == "blob":
            b = self.store.get(fields["hash"])
            if b is None:
                return {"ok": False, "error": "unknown blob"}
            return {"ok": True, "blob": b}
        raise AssertionError(method)


class TestBatchedFetchFallback:
    """The silent-partial-batch fix: a batched reply that omits a hash
    costs counted per-hash round-trips, never silence or a crash."""

    def test_omitted_hash_falls_back_per_hash_and_counts(self):
        blobs = {hashlib.sha256(bytes([i]) * 64).hexdigest():
                 bytes([i]) * 64 for i in range(3)}
        stub = _StubControl(blobs)
        was_enabled = obs_metrics.REGISTRY.enabled
        obs_metrics.REGISTRY.enabled = True
        try:
            from bflc_demo_tpu.comm import dataplane as dp
            before = sum(
                s["value"] for s in dp._M_FALLBACK.samples())
            router = ReadRouter(stub)
            out = router.fetch_blobs(sorted(blobs))
            assert out == {h: blobs[h] for h in blobs}
            after = sum(s["value"] for s in dp._M_FALLBACK.samples())
            # the batch served 1 of 3: two per-hash fallbacks, counted
            assert after - before == 2
            assert stub.calls.count("blob") == 2
        finally:
            obs_metrics.REGISTRY.enabled = was_enabled

    def test_totally_missing_hash_raises_lookup_error(self):
        stub = _StubControl({})
        router = ReadRouter(stub)
        with pytest.raises(LookupError):
            router.fetch_blobs(["ab" * 32])


class TestReadSetAdvertisement:
    """End-to-end: an authenticated standby advertises its read endpoint
    at subscribe time, the writer republishes it in model replies, and a
    router's reads actually land on the replica."""

    def test_standby_read_ep_advertised_and_served(self):
        from bflc_demo_tpu.comm.failover import Standby
        from bflc_demo_tpu.comm.identity import Wallet
        wallet = Wallet.from_seed(b"dp-readset-standby-1")
        standby_keys = {1: wallet.public_bytes}
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0,
                           ledger_backend="python",
                           standby_keys=standby_keys)
        srv.start()
        sb = Standby(CFG, [(srv.host, srv.port), ("127.0.0.1", 0)], 1,
                     ledger_backend="python", wallet=wallet,
                     standby_keys=standby_keys, heartbeat_s=0.2)
        t = threading.Thread(target=sb.run, daemon=True)
        t.start()
        try:
            ctl = CoordinatorClient(srv.host, srv.port)
            deadline = time.monotonic() + 20.0
            meta = {}
            while time.monotonic() < deadline:
                meta = ctl.request("model", meta=1)
                if meta.get("read_set"):
                    break
                time.sleep(0.2)
            assert meta.get("read_set") == \
                [list(sb.read_server.endpoint)], meta
            assert "blob" not in meta           # meta probe carries none
            # wait for the standby to mirror the genesis model, then a
            # fresh router's model bytes must come FROM the replica
            while time.monotonic() < deadline and sb._model_blob is None:
                time.sleep(0.1)
            router = ReadRouter(ctl)
            router.note_read_set(meta)      # as a live client would
            mr = router.fetch_model()
            assert mr["ok"] and mr["blob"] == _init_blob()
            assert mr["source"] == "replica", mr["source"]
            # second fetch of the unchanged model: pure cache hit
            assert router.fetch_model()["source"] == "cache"
            ctl.close()
        finally:
            sb.stop()
            srv.close()

    def test_anonymous_subscriber_read_ep_ignored(self):
        """An unauthenticated subscriber must not enter the read set (it
        could sinkhole reads for a round-trip each)."""
        from bflc_demo_tpu.comm.wire import recv_msg, send_msg
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           standby_keys={1: b"\x01" * 32})
        srv.start()
        try:
            sub = CoordinatorClient(srv.host, srv.port)
            send_msg(sub.sock, {"method": "subscribe", "from": 0,
                                "read_ep": ["127.0.0.1", 1]})
            time.sleep(0.5)
            ctl = CoordinatorClient(srv.host, srv.port)
            meta = ctl.request("model", meta=1)
            assert not meta.get("read_set"), meta
            ctl.close()
            sub.close()
        finally:
            srv.close()


def _drive_round(server, delta_dtype: str):
    """One full protocol round over the socket with `delta_dtype`
    uploads; returns the committed flat model."""
    c = CoordinatorClient(server.host, server.port)
    addrs = [f"0x{i:040x}" for i in range(CFG.client_num)]
    for a in addrs:
        assert c.request("register", addr=a)["ok"]
    committee = c.request("committee")["committee"]
    trainers = [a for a in addrs if a not in committee]
    for i, a in enumerate(trainers[:3]):
        blob = (pack_pytree(_delta(float(i + 1)))
                if delta_dtype == "f32"
                else pack_quantized(_delta(float(i + 1)), delta_dtype))
        digest = hashlib.sha256(blob).digest()
        r = c.request("upload", addr=a, blob=blob, hash=digest.hex(),
                      n=100, cost=1.0, epoch=0)
        assert r["ok"], r
    for j, comm in enumerate(committee):
        scores = [0.9, 0.5, 0.1] if j == 0 else [0.8, 0.6, 0.2]
        assert c.request("scores", addr=comm, epoch=0,
                         scores=scores)["ok"]
    assert c.request("info")["epoch"] == 1      # aggregation fired
    mr = c.request("model")
    from bflc_demo_tpu.comm.wire import blob_bytes
    flat = unpack_pytree(blob_bytes(mr["blob"]))
    c.close()
    return flat


class TestQuantizedDeltas:
    """Opt-in reduced-precision uploads: the hash the ledger certifies
    is over the QUANTIZED canonical bytes; admission, scoring and
    aggregation all decode through the one shared dequantizer."""

    @pytest.mark.parametrize("dtype", ["f16", "i8"])
    def test_quantized_round_aggregates_close_to_f32(self, dtype):
        cfg_q = dataclasses.replace(CFG, delta_dtype=dtype).validate()
        srv_f = LedgerServer(CFG, _init_blob(), require_auth=False,
                             stall_timeout_s=60.0,
                             ledger_backend="python")
        srv_q = LedgerServer(cfg_q, _init_blob(), require_auth=False,
                             stall_timeout_s=60.0,
                             ledger_backend="python")
        srv_f.start()
        srv_q.start()
        try:
            ref = _drive_round(srv_f, "f32")
            got = _drive_round(srv_q, dtype)
            # deltas are constants (exactly representable at f16; i8
            # rounds to the max-scale grid): aggregation must land
            # within one i8 quantization step of the f32 result
            for key in ref:
                np.testing.assert_allclose(
                    got[key], ref[key], atol=CFG.learning_rate * 3 / 127)
        finally:
            srv_f.close()
            srv_q.close()

    def test_quantized_upload_rejected_when_opted_out(self):
        """delta_dtype=f32 (the default) keeps the strict pre-PR
        admission: a reduced-precision blob is BAD_ARG at the door."""
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        try:
            c = CoordinatorClient(srv.host, srv.port)
            for i in range(CFG.client_num):
                assert c.request("register",
                                 addr=f"0x{i:040x}")["ok"]
            committee = c.request("committee")["committee"]
            trainer = next(f"0x{i:040x}" for i in range(CFG.client_num)
                           if f"0x{i:040x}" not in committee)
            blob = pack_quantized(_delta(1.0), "i8")
            digest = hashlib.sha256(blob).digest()
            r = c.request("upload", addr=trainer, blob=blob,
                          hash=digest.hex(), n=100, cost=1.0, epoch=0)
            assert not r["ok"] and r["status"] == "BAD_ARG", r
            c.close()
        finally:
            srv.close()

    def test_dequantization_is_deterministic(self):
        rng = np.random.default_rng(7)
        flat = {"['W']": rng.standard_normal((64, 8)).astype(np.float32)}
        b1 = pack_quantized({"W": flat["['W']"]}, "i8")
        b2 = pack_quantized({"W": flat["['W']"]}, "i8")
        assert b1 == b2                 # signed bytes are reproducible
        d1 = dequantize_entries(unpack_pytree(b1))
        d2 = dequantize_entries(unpack_pytree(b2))
        np.testing.assert_array_equal(d1["['W']"], d2["['W']"])
        assert d1["['W']"].dtype == np.float32
