"""Async committee re-election + production endurance (ISSUE 16): the
deterministic reseat rule (every R-th buffered drain reseats the
committee from the drained window's median-score ranking), its
replay/snapshot determinism properties, the lying-writer refusals, the
R=0 / BFLC_ASYNC_LEGACY byte pins, the churn chaos profile and its
"+"-composition, adaptive SLO baselining, and the tier-1 twin of the
multi-thousand-round endurance campaign (bench.py
extra.endurance_async).
"""

import dataclasses
import hashlib
import random
import struct

import pytest

from bflc_demo_tpu.ledger import LedgerStatus, async_enabled, make_ledger
from bflc_demo_tpu.ledger.pyledger import _OP_ACOMMIT, _put_str
from bflc_demo_tpu.ledger.snapshot import decode_state, restore_snapshot
from bflc_demo_tpu.protocol.constants import ProtocolConfig

RCFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                      needed_update_count=3, learning_rate=0.05,
                      batch_size=16, async_buffer=3, max_staleness=4,
                      async_reseat_every=2).validate()


def _h(tag) -> bytes:
    return hashlib.sha256(repr(tag).encode()).digest()


def _led(cfg=RCFG):
    led = make_ledger(cfg)
    for i in range(cfg.client_num):
        assert led.register_node(f"c{i}") == LedgerStatus.OK
    return led


def _drain(led, senders, scores=None, scorer=None):
    """One buffered round: fill from `senders`, optionally score every
    live entry, drain all of them."""
    ep = led.epoch
    for j, s in enumerate(senders):
        assert led.async_upload(s, _h((ep, s)), 10 + j, 1.0,
                                ep) == LedgerStatus.OK
    if scores is not None:
        who = scorer or led.committee()[0]
        live = [e.aseq for e in led.async_buffer_view()]
        assert led.async_scores(
            who, list(zip(live, scores))) == LedgerStatus.OK
    assert led.async_commit(_h(("m", ep)), ep,
                            len(senders)) == LedgerStatus.OK


def _replay(led, cfg=RCFG):
    replica = make_ledger(cfg)
    for i in range(led.log_size()):
        assert replica.apply_op(led.log_op(i)) == LedgerStatus.OK
    return replica


class TestReseatRule:
    def test_due_schedule_and_seating_from_window(self):
        led = _led()
        genesis_committee = led.committee()
        # R=2: the first drain keeps the genesis committee, the second
        # reseats it from the drained window's score ranking
        assert not led.async_reseat_due()
        _drain(led, ["c0", "c1", "c2"], [0.5, 0.5, 0.5])
        assert led.committee() == genesis_committee
        assert led.async_reseat_due()
        # rank the window: c4 (0.9) then c5 (0.6) — those two get seated
        ep = led.epoch
        for j, s in enumerate(["c3", "c4", "c5"]):
            assert led.async_upload(s, _h((ep, s)), 10 + j, 1.0,
                                    ep) == LedgerStatus.OK
        live = [e.aseq for e in led.async_buffer_view()]
        assert led.async_scores(
            led.committee()[0],
            list(zip(live, [0.1, 0.9, 0.6]))) == LedgerStatus.OK
        derived = led.derive_async_seats(3)
        assert derived == ["c4", "c5"]
        assert led.async_commit(_h(("m", ep)), ep, 3) == LedgerStatus.OK
        assert set(led.committee()) == {"c4", "c5"}
        # the counter reset the cadence: next drain is not a reseat
        assert not led.async_reseat_due()

    def test_unscored_window_tops_up_from_incumbents(self):
        """A reseat over an unscored window still seats comm_count
        addresses deterministically (rank ties at 0.0 → aseq order,
        top-up scans registration order)."""
        led = _led()
        _drain(led, ["c0", "c1", "c2"])             # no scores at all
        ep = led.epoch
        assert led.async_reseat_due()
        for s in ["c3", "c4"]:
            assert led.async_upload(s, _h((ep, s)), 10, 1.0,
                                    ep) == LedgerStatus.OK
        derived = led.derive_async_seats(2)
        assert len(derived) == RCFG.comm_count
        assert derived == ["c3", "c4"]              # aseq order at 0.0
        assert led.async_commit(_h(("m", ep)), ep, 2) == LedgerStatus.OK
        assert set(led.committee()) == set(derived)


class TestReseatDeterminismProperty:
    """Every role derives the identical seating: full-chain replicas,
    snapshot-restored standbys joining mid-reseat-window, and the
    writer itself — across randomized arrival orders and scorings."""

    @pytest.mark.parametrize("seed", range(4))
    def test_shuffled_arrivals_replica_and_snapshot_agree(self, seed):
        rng = random.Random(seed)
        led = _led()
        mid, mid_pos = None, 0
        for r in range(6):
            senders = rng.sample([f"c{i}" for i in range(6)], 3)
            scores = [round(rng.random(), 3) for _ in senders]
            scorer = rng.choice(led.committee())
            _drain(led, senders, scores, scorer)
            if r == 2:
                # a standby state-syncs mid-window (R=2: after drain 3
                # the counter sits mid-cadence) and must re-derive the
                # remaining reseats identically
                mid_pos = led.log_size()
                mid = restore_snapshot(led.encode_state(), RCFG,
                                       mid_pos, led.log_head())
                assert mid.async_reseat_due() == led.async_reseat_due()
        # a full-chain replica replays every op
        replica = _replay(led)
        assert replica.log_head() == led.log_head()
        assert replica.state_digest() == led.state_digest()
        assert replica.committee() == led.committee()
        # the mid-run standby continues from its chain position
        for i in range(mid_pos, led.log_size()):
            assert mid.apply_op(led.log_op(i)) == LedgerStatus.OK
        assert mid.log_head() == led.log_head()
        assert mid.state_digest() == led.state_digest()
        assert mid.committee() == led.committee()

    def test_crash_rejoin_mid_window_via_wal(self, tmp_path):
        """A writer crash between the (R-1)-th and R-th drain: WAL
        replay restores the acommit counter, so the rejoined process
        reseats on the exact drain the dead one would have."""
        path = str(tmp_path / "reseat.wal")
        led = _led()
        assert led.attach_wal(path)
        _drain(led, ["c0", "c1", "c2"], [0.5, 0.4, 0.3])
        due_before = led.async_reseat_due()
        assert due_before                           # mid-window crash
        led.detach_wal()
        risen = make_ledger(RCFG)
        assert risen.replay_wal(path) > 0
        assert risen.log_head() == led.log_head()
        assert risen.async_reseat_due() == due_before
        _drain(risen, ["c3", "c4", "c5"], [0.2, 0.9, 0.1])
        _drain(led, ["c3", "c4", "c5"], [0.2, 0.9, 0.1])
        assert risen.committee() == led.committee()
        assert set(risen.committee()) == {"c3", "c4"}
        assert risen.log_head() == led.log_head()
        assert risen.state_digest() == led.state_digest()


class TestLyingWriterRefused:
    """The seating claim embedded in an extended ACOMMIT body is
    re-derived by every replica; disagreement is BAD_ARG — the op never
    certifies at a BFT quorum."""

    def _at_due_drain(self):
        led = _led()
        _drain(led, ["c0", "c1", "c2"], [0.5, 0.4, 0.3])
        replica = _replay(led)
        ep = led.epoch
        for j, s in enumerate(["c3", "c4", "c5"]):
            for node in (led, replica):
                assert node.async_upload(s, _h((ep, s)), 10 + j, 1.0,
                                         ep) == LedgerStatus.OK
        assert led.async_reseat_due() and replica.async_reseat_due()
        return led, replica, ep

    @staticmethod
    def _acommit_op(mh, ep, k, seats):
        op = bytearray([_OP_ACOMMIT])
        op += mh + struct.pack("<qq", ep, k)
        if seats is not None:
            op += struct.pack("<q", len(seats))
            for a in seats:
                _put_str(op, a)
        return bytes(op)

    def test_forged_seating_refused_then_honest_one_lands(self):
        led, replica, ep = self._at_due_drain()
        honest = led.derive_async_seats(3)
        lie = ["c0", "c1"]
        assert lie != honest
        before = replica.state_digest()
        assert replica.apply_op(self._acommit_op(
            _h(("m", ep)), ep, 3, lie)) == LedgerStatus.BAD_ARG
        assert replica.state_digest() == before     # refusal is pure
        # a due drain claiming NO reseat (plain 48-byte body) also dies
        assert replica.apply_op(self._acommit_op(
            _h(("m", ep)), ep, 3, None)) == LedgerStatus.BAD_ARG
        # the honest writer's op replays cleanly
        assert led.async_commit(_h(("m", ep)), ep, 3) == LedgerStatus.OK
        assert replica.apply_op(
            led.log_op(led.log_size() - 1)) == LedgerStatus.OK
        assert replica.committee() == led.committee() == honest

    def test_seating_on_a_non_due_drain_refused(self):
        led = _led()
        ep = led.epoch
        for j, s in enumerate(["c0", "c1", "c2"]):
            assert led.async_upload(s, _h((ep, s)), 10 + j, 1.0,
                                    ep) == LedgerStatus.OK
        assert not led.async_reseat_due()
        assert led.apply_op(self._acommit_op(
            _h(("m", ep)), ep, 3,
            ["c0", "c1"])) == LedgerStatus.BAD_ARG

    def test_malformed_extension_refused(self):
        led, replica, ep = self._at_due_drain()
        honest = led.derive_async_seats(3)
        good = self._acommit_op(_h(("m", ep)), ep, 3, honest)
        assert replica.apply_op(good + b"\x00") == LedgerStatus.BAD_ARG
        zero = self._acommit_op(_h(("m", ep)), ep, 3, [])
        assert replica.apply_op(zero) == LedgerStatus.BAD_ARG


class TestLegacyBytePins:
    """R=0 (the default) and BFLC_ASYNC_LEGACY=1 pin the pre-reseat
    byte formats exactly: no acommit-counter tail in the canonical
    state, golden chain/state digests unchanged run over run."""

    # digests captured from the frozen-committee async format (R=0):
    # any drift in the ACOMMIT codec or the canonical state layout for
    # non-reseating chains fails here
    GOLDEN_R0_HEAD = ("af0cf91c0e7ac131616a4a9c95f07985"
                      "6c5e14e34c30838be89c64f37ab5d714")
    GOLDEN_R0_STATE = ("eaf08845ece8b23bdbf8040973f53250"
                       "206eaf99c886c5cdb19df6345601a324")

    @staticmethod
    def _scripted_r0():
        cfg = dataclasses.replace(RCFG,
                                  async_reseat_every=0).validate()
        led = make_ledger(cfg)
        for i in range(cfg.client_num):
            assert led.register_node(f"c{i}") == LedgerStatus.OK
        scorer = led.committee()[0]
        for ep in range(2):
            for j, s in enumerate(["c0", "c1", "c2"]):
                assert led.async_upload(s, _h((ep, s)), 10 + j, 1.0,
                                        ep) == LedgerStatus.OK
            live = [e.aseq for e in led.async_buffer_view()]
            assert led.async_scores(
                scorer,
                list(zip(live, [0.2, 0.9, 0.5]))) == LedgerStatus.OK
            assert led.async_commit(_h(("m", ep)), ep,
                                    3) == LedgerStatus.OK
        return led

    def test_r0_twin_runs_byte_identical_and_pinned(self):
        a, b = self._scripted_r0(), self._scripted_r0()
        assert a.log_head() == b.log_head()
        assert a.encode_state() == b.encode_state()
        assert a.log_head().hex() == self.GOLDEN_R0_HEAD
        assert hashlib.sha256(
            a.encode_state()).hexdigest() == self.GOLDEN_R0_STATE
        # no reseat cadence -> no counter tail in the canonical state
        assert decode_state(a.encode_state())["async_acommits"] is None
        assert not a.async_reseat_due()

    def test_r_positive_state_carries_and_restores_the_counter(self):
        led = _led()
        _drain(led, ["c0", "c1", "c2"], [0.5, 0.4, 0.3])
        d = decode_state(led.encode_state())
        assert d["async_acommits"] == 1
        r = restore_snapshot(led.encode_state(), RCFG, led.log_size(),
                             led.log_head())
        assert led.async_reseat_due()
        assert r.async_reseat_due()

    def test_async_legacy_env_disables_the_reseat_family(self,
                                                         monkeypatch):
        monkeypatch.setenv("BFLC_ASYNC_LEGACY", "1")
        assert not async_enabled(RCFG)
        led = make_ledger(RCFG)
        assert getattr(led, "async_buffer", 0) == 0

    def test_reseat_requires_async_buffer(self):
        with pytest.raises(ValueError, match="async_reseat_every"):
            dataclasses.replace(RCFG, async_buffer=0,
                                async_reseat_every=2).validate()


class TestChurnSchedule:
    def _mk(self, profile, seed=7):
        from bflc_demo_tpu.chaos.schedule import FaultSchedule
        return FaultSchedule(seed, duration_s=120, n_clients=6,
                             n_standbys=1, n_validators=4,
                             profile=profile)

    def test_churn_profile_seeded_floor_and_cap(self):
        s1, s2 = self._mk("churn"), self._mk("churn")
        assert [e.as_dict() for e in s1.events] == \
            [e.as_dict() for e in s2.events]
        assert s1.events, "a 120s churn campaign must move members"
        assert {e.kind for e in s1.events} <= {"retire", "join"}
        assert not s1.wire_windows          # membership only, no wire
        live = set(range(6))
        floor = max(2, round(6 * 0.5))
        joined = 0
        for e in sorted(s1.events, key=lambda e: e.t):
            i = int(e.target.split("-")[1])
            if e.kind == "retire":
                live.discard(i)
                assert len(live) >= floor
            else:
                assert i >= 6               # fresh index, never reuse
                live.add(i)
                joined += 1
        assert joined <= round(6 * 2.0)

    def test_composition_overlays_without_perturbing_parts(self):
        both = self._mk("heavytail+churn")
        churn = self._mk("churn")
        # composed parts draw from derived per-part streams: the same
        # seed gives the composed campaign heavytail's wire shape AND a
        # churn trajectory, each deterministic in its own right
        assert set(both.wire_windows) == {f"client-{i}"
                                          for i in range(6)}
        assert {e.kind for e in both.events} <= {"retire", "join"}
        assert both.events
        again = self._mk("heavytail+churn")
        assert [e.as_dict() for e in both.events] == \
            [e.as_dict() for e in again.events]
        assert {r: [w.as_dict() for w in ws]
                for r, ws in both.wire_windows.items()} == \
            {r: [w.as_dict() for w in ws]
             for r, ws in again.wire_windows.items()}
        # single-name profiles keep their pre-composition rng stream
        solo1, solo2 = self._mk("churn"), self._mk("churn")
        assert [e.as_dict() for e in solo1.events] == \
            [e.as_dict() for e in solo2.events]
        assert churn.events  # and the solo stream still yields churn

    def test_unknown_and_empty_compositions_refused(self):
        with pytest.raises(ValueError, match="unknown chaos profile"):
            self._mk("heavytail+nope")
        with pytest.raises(ValueError, match="unknown chaos profile"):
            self._mk("+")


class TestAdaptiveSLO:
    def _spec(self, **kw):
        from bflc_demo_tpu.obs.slo import SLOSpec
        kw.setdefault("budget", 0.2)
        return SLOSpec("lat", "v", 30.0, warmup=4, adapt_mult=4.0,
                       adapt_floor=0.1, **kw)

    def test_warmup_collects_then_learns_a_tight_bound(self):
        from bflc_demo_tpu.obs.slo import SLOEngine
        eng = SLOEngine([self._spec()])
        for v in (1.0, 1.1, 1.2, 1.3):      # warmup: collected, not judged
            assert eng.observe_round({"epoch": 0, "v": v}) == []
        rep = eng.report()["slos"]["lat"]
        assert rep["judged"] == 0 and rep["warmup_collected"] == 4
        lb = rep["learned_bound"]
        assert lb is not None and lb < 30.0
        # median 1.15, MAD 0.1 -> 1.15 + 4*0.1 = 1.55
        assert lb == pytest.approx(1.55, abs=1e-6)
        # a value healthy vs the static bound but sick vs the learned
        # one now breaches
        assert eng.observe_round({"epoch": 1, "v": 5.0}) == []
        assert eng.report()["slos"]["lat"]["breaches"] == 1
        assert eng.observe_round({"epoch": 2, "v": 1.2}) == []
        assert eng.report()["slos"]["lat"]["breaches"] == 1

    def test_learned_bound_never_laxer_than_static(self):
        spec = self._spec()
        assert spec.learn_bound([100.0, 100.0, 100.0]) == 30.0
        ge = self._spec(op=">=")
        # ">=" mirror: learned bound can only RISE above the static
        assert ge.learn_bound([100.0, 100.0, 100.0]) >= 30.0

    def test_adaptive_env_parse(self, monkeypatch):
        from bflc_demo_tpu.obs.slo import adaptive_warmup, default_slos
        monkeypatch.setenv("BFLC_SLO_ADAPTIVE", "17")
        assert adaptive_warmup() == 17
        slos = {s.name: s for s in default_slos()}
        assert slos["round_latency"].warmup == 17
        assert slos["certify_latency"].warmup == 17
        assert slos["async_staleness"].warmup == 0   # principled bound
        monkeypatch.setenv("BFLC_SLO_ADAPTIVE", "banana")
        assert adaptive_warmup() == 0

    def test_rederive_skip_objective_judges_the_counter_delta(self):
        from bflc_demo_tpu.obs.slo import SLOEngine, default_slos
        slos = [s for s in default_slos()
                if s.name == "rederive_skip"]
        assert slos and slos[0].bound == 0.0
        eng = SLOEngine(slos)
        eng.observe_round({"epoch": 0, "rederive_skipped_delta": 0.0})
        assert eng.report()["slos"]["rederive_skip"]["breaches"] == 0
        for ep in range(1, 4):
            eng.observe_round({"epoch": ep,
                               "rederive_skipped_delta": 2.0})
        rep = eng.report()["slos"]["rederive_skip"]
        assert rep["breaches"] == 3 and rep["alerts"] >= 1


class TestEnduranceAsyncCampaign:
    """The headline artifact, tier-1 twin geometry: every acceptance
    criterion of the 2,000-round campaign at a 240-round scale that
    fits the tier-1 budget (measured well under a second)."""

    def _assert_campaign(self, out):
        assert out["epochs_monotone"], out
        assert out["reseats"] > 0, out
        assert len(out["final_committee"]) == 3, out
        assert out["clients_retired"] > 0, out
        assert out["clients_joined"] > 0, out
        assert out["stale_admitted"] > 0, out
        assert out["stale_refused"] > 0, out
        # churned-out senders' in-flight deltas never wedge the buffer
        assert out["departed_wedged"] == 0, out
        # every role derives the identical seating
        assert out["replica_agrees"], out
        assert out["state_synced_mid_reseat_window"], out
        # bounded memory + bounded WAL: the second half's ceilings do
        # not exceed the first's (+1 op of commit-size slack)
        assert out["second_half_max_wal_bytes"] <= \
            out["first_half_max_wal_bytes"] + 512, out
        assert out["second_half_max_held_ops"] <= \
            out["first_half_max_held_ops"] + 4, out
        # adaptive SLOs judged every post-warmup round, zero false pages
        assert out["slo_false_pages"] == 0, out
        assert out["slo"]["rounds_judged"] == out["rounds"], out

    def test_tier1_twin_240_rounds(self):
        from bflc_demo_tpu.eval.benchmarks import endurance_async_config1
        out = endurance_async_config1(rounds=240, reseat_every=10,
                                      snapshot_interval=32,
                                      churn_every=8, slo_warmup=20)
        assert out["rounds"] == 240 and out["final_epoch"] == 240, out
        assert out["reseats"] == 24, out
        self._assert_campaign(out)

    @pytest.mark.slow
    def test_full_campaign_2000_rounds(self):
        from bflc_demo_tpu.eval.benchmarks import endurance_async_config1
        out = endurance_async_config1()
        assert out["rounds"] == 2000, out
        assert out["reseats"] == 80, out
        self._assert_campaign(out)
