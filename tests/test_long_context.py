"""Long-context at real lengths: ring attention beyond toy sequences.

The per-shard equivalence tests (test_ring_attention.py) run at seq 32;
these run the lengths the mechanism exists for — 8k with a bit-exact
differential against the single-device forward, 32k ring-only (the
single-device einsum would materialise a 2x32k^2 f32 logits tensor there,
which is exactly the regime ring attention removes).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.models.transformer import (make_transformer_classifier,
                                              transformer_forward)
from bflc_demo_tpu.parallel.mesh import make_mesh
from bflc_demo_tpu.parallel.ring_attention import (SP_AXIS,
                                                   make_sp_transformer_forward)


def _setup(seq_len, real_len, seed=0):
    model = make_transformer_classifier(vocab_size=128, seq_len=seq_len,
                                        num_classes=2, dim=16, depth=1,
                                        heads=2)
    rng = np.random.default_rng(seed)
    toks = np.zeros((2, seq_len), np.int32)
    toks[:, :real_len] = rng.integers(1, 128, (2, real_len))
    return model, jnp.asarray(toks)


@pytest.mark.slow
def test_8k_matches_single_device_exactly():
    """At seq 8192 over 8 sequence shards the ring forward reproduces the
    single-device forward (measured bit-exact on CPU: same reduction order
    per block, f32 streaming softmax)."""
    model, toks = _setup(8192, 300)
    mesh = make_mesh((8,), (SP_AXIS,))
    got = make_sp_transformer_forward(mesh, model.config)(
        model.init_params(0), toks)
    want = transformer_forward(model.init_params(0), toks, model.config)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_32k_ring_runs_and_attends():
    """Seq 32768 on the 8-device mesh: finite logits, and the output is
    actually sensitive to a single resident token (the ring really carried
    information, it didn't just mask everything)."""
    model, toks = _setup(32768, 200)
    mesh = make_mesh((8,), (SP_AXIS,))
    params = model.init_params(0)
    fn = make_sp_transformer_forward(mesh, model.config)
    out = np.asarray(fn(params, toks))
    assert out.shape == (2, 2) and np.isfinite(out).all()
    toks2 = np.array(toks)
    toks2[0, 5] = (toks2[0, 5] % 127) + 1       # different non-PAD token
    out2 = np.asarray(fn(params, jnp.asarray(toks2)))
    assert np.any(np.abs(out2[0] - out[0]) > 0)
    np.testing.assert_allclose(out2[1], out[1], rtol=1e-6)  # batch isolated
