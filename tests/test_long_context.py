"""Long-context at real lengths: ring attention beyond toy sequences.

The per-shard equivalence tests (test_ring_attention.py) run at seq 32;
these run the lengths the mechanism exists for — 8k with a differential
against the single-device forward, and a (seq, real_len, n_sp) regression
matrix up to 32k against a CHUNKED single-device reference (query chunks
over the full K/V — exact softmax per row, never an (S, S) logits tensor),
which is the only tractable exact oracle at 16k/32k.

Round-3 post-mortem baked into these tests: the old versions used the
model's initial parameters, whose classifier head is zero-initialised
(models/transformer.py init_transformer_params: head_w = zeros) — so the
logits were identically [0, 0] for ANY input at ANY length and the
"bit-exact" 8k comparison was vacuously comparing zeros while the 32k
input-sensitivity assertion could never pass.  `_setup` now gives the head
seeded nonzero weights so every comparison below actually witnesses
information flowing through the ring.  `test_head_is_nonzero` pins that
precondition so the vacuity cannot silently return.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.models.transformer import (NEG_INF,
                                              make_transformer_classifier,
                                              transformer_forward)
from bflc_demo_tpu.parallel.mesh import make_mesh
from bflc_demo_tpu.parallel.ring_attention import (SP_AXIS,
                                                   make_sp_transformer_forward)


def _setup(seq_len, real_len, seed=0):
    model = make_transformer_classifier(vocab_size=128, seq_len=seq_len,
                                        num_classes=2, dim=16, depth=1,
                                        heads=2)
    params = model.init_params(0)
    # the classifier head is zero-initialised by design (FL rounds train it);
    # for forward-equivalence tests that makes the logits a constant [0, 0]
    # and every comparison vacuous — give it seeded nonzero weights so the
    # logits are a faithful witness of the pooled representation
    hk = jax.random.PRNGKey(seed + 17)
    params["head_w"] = jax.random.normal(hk, params["head_w"].shape,
                                         jnp.float32) * 0.5
    params["head_b"] = jnp.asarray([0.1, -0.2], jnp.float32)
    rng = np.random.default_rng(seed)
    toks = np.zeros((2, seq_len), np.int32)
    toks[:, :real_len] = rng.integers(1, 128, (2, real_len))
    return model, params, jnp.asarray(toks)


def _chunked_attn(cfg, chunk=256):
    """Exact single-device attention oracle that never materialises the
    (S, S) logits: plain softmax per query chunk over the FULL key set.
    Eager op-by-op (no jit) so 32k costs memory proportional to
    chunk x S, not S x S."""
    scale = 1.0 / np.sqrt(cfg.head_dim)

    def attn(q, k, v, kv_mask):
        outs = []
        for i in range(0, q.shape[1], chunk):
            qc = q[:, i:i + chunk]
            logits = (jnp.einsum("bqhd,bkhd->bhqk", qc, k)
                      .astype(jnp.float32) * scale)
            logits = jnp.where(kv_mask[:, None, None, :], logits, NEG_INF)
            p = jax.nn.softmax(logits, axis=-1)
            # zero fully-masked rows (plain softmax yields uniform there;
            # the ring yields 0) — both are pooled away by the pad mask,
            # but zeroing makes the oracle comparable PER TOKEN too
            p = jnp.where(kv_mask[:, None, None, :], p, 0.0)
            denom = p.sum(-1, keepdims=True)
            p = p / jnp.maximum(denom, 1e-30)
            outs.append(jnp.einsum("bhqk,bkhd->bqhd", p,
                                   v.astype(jnp.float32)).astype(q.dtype))
        return jnp.concatenate(outs, axis=1)

    return attn


def test_head_is_nonzero():
    """Pin the vacuity guard: _setup must hand back a head whose logits
    respond to the pooled features (round-3's 32k 'ring bug' was really a
    zero head making the logits constant)."""
    _, params, _ = _setup(64, 20)
    assert float(jnp.abs(params["head_w"]).sum()) > 0


@pytest.mark.slow
def test_8k_matches_single_device_exactly():
    """At seq 8192 over 8 sequence shards the ring forward reproduces the
    single-device forward (same f32 streaming softmax math; tolerance covers
    the streaming-vs-plain reduction order)."""
    model, params, toks = _setup(8192, 300)
    mesh = make_mesh((8,), (SP_AXIS,))
    got = make_sp_transformer_forward(mesh, model.config)(params, toks)
    want = transformer_forward(params, toks, model.config)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("seq_len,real_len,n_sp", [
    (4096, 4096, 8),      # no padding at all
    (8192, 2500, 4),      # real tokens SPAN a shard boundary (s_blk=2048)
    (16384, 5000, 8),     # spans shards 0-2; shards 3-7 fully PAD
    (32768, 200, 8),      # the round-3 regime: 7 of 8 shards fully PAD
])
def test_ring_matrix_matches_chunked_reference(seq_len, real_len, n_sp):
    """Regression matrix over (seq, real_len, n_sp): the ring forward equals
    the chunked exact oracle at every geometry, including real tokens
    spanning shard boundaries and majority-all-PAD shard sets."""
    model, params, toks = _setup(seq_len, real_len, seed=seq_len % 97)
    mesh = make_mesh((n_sp,), (SP_AXIS,))
    got = np.asarray(
        make_sp_transformer_forward(mesh, model.config)(params, toks))
    want = np.asarray(transformer_forward(
        params, toks, model.config, attn_fn=_chunked_attn(model.config)))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)


@pytest.mark.slow
def test_32k_ring_runs_and_attends():
    """Seq 32768 on the 8-device mesh: finite logits, and the output is
    actually sensitive to a single resident token (the ring really carried
    information, it didn't just mask everything)."""
    model, params, toks = _setup(32768, 200)
    mesh = make_mesh((8,), (SP_AXIS,))
    fn = make_sp_transformer_forward(mesh, model.config)
    out = np.asarray(fn(params, toks))
    assert out.shape == (2, 2) and np.isfinite(out).all()
    toks2 = np.array(toks)
    toks2[0, 5] = (toks2[0, 5] % 127) + 1       # different non-PAD token
    out2 = np.asarray(fn(params, jnp.asarray(toks2)))
    assert np.any(np.abs(out2[0] - out[0]) > 0)
    np.testing.assert_allclose(out2[1], out[1], rtol=1e-6)  # batch isolated


@pytest.mark.slow
def test_32k_sensitivity_across_shard_boundary():
    """Perturbing a token resident on shard 1 (not the query-holding shard 0
    block only) changes the logits: the ring hop genuinely moved KV between
    devices at 32k, it didn't only attend locally."""
    model, params, toks = _setup(32768, 5000, seed=3)   # spans shards 0-1
    mesh = make_mesh((8,), (SP_AXIS,))
    fn = make_sp_transformer_forward(mesh, model.config)
    out = np.asarray(fn(params, toks))
    toks2 = np.array(toks)
    assert 4096 < 4999 < 8192                           # resident on shard 1
    toks2[0, 4999] = (toks2[0, 4999] % 127) + 1
    out2 = np.asarray(fn(params, jnp.asarray(toks2)))
    assert np.any(np.abs(out2[0] - out[0]) > 0)


@pytest.mark.slow
def test_long_context_training_step_4k():
    """Long-context TRAINING at a real length: one SGD step at seq 4096
    over 8 sequence shards — gradients flow backward through the ring —
    matches the single-device step (the round-5 sp-training surface,
    make_sp_train_step, at a length where shard boundaries are real)."""
    from bflc_demo_tpu.parallel.ring_attention import (SP_AXIS,
                                                       make_sp_train_step)
    model, params, toks = _setup(4096, 3500, seed=11)
    cfg = model.config
    rng = np.random.default_rng(11)
    labels = jnp.asarray(np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, toks.shape[0])])

    def loss_fn(p):
        logits = transformer_forward(p, jnp.asarray(toks), cfg)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))

    want_l, g = jax.value_and_grad(loss_fn)(params)
    want_p = jax.tree_util.tree_map(
        lambda w, d: w - jnp.asarray(0.1, w.dtype) * d.astype(w.dtype),
        params, g)

    mesh = make_mesh((8,), (SP_AXIS,))
    step = make_sp_train_step(mesh, cfg, lr=0.1)
    got_p, got_l = step(params, jnp.asarray(toks), labels)
    np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-4)
    for w, gp in zip(jax.tree_util.tree_leaves(want_p),
                     jax.tree_util.tree_leaves(got_p)):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(w),
                                   rtol=5e-4, atol=5e-5)
