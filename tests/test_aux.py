"""Aux subsystem tests: tracing/cost accounting, checkpoint/resume,
failure recovery (threaded runtime + ledger recovery ops), config system."""

import os

import numpy as np
import pytest

from bflc_demo_tpu.data import load_occupancy, iid_shards
from bflc_demo_tpu.ledger import make_ledger, LedgerStatus
from bflc_demo_tpu.models import make_softmax_regression
from bflc_demo_tpu.protocol import ProtocolConfig, DEFAULT_PROTOCOL
from bflc_demo_tpu.utils.tracing import Tracer
from bflc_demo_tpu.utils.flags import parse_args, protocol_from_env

SMALL = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                       needed_update_count=3, learning_rate=0.001,
                       batch_size=50, local_epochs=1)


@pytest.fixture(scope="module")
def small_data():
    xtr, ytr, xte, yte = load_occupancy()
    return iid_shards(xtr[:2000], ytr[:2000], SMALL.client_num), \
        (xte[:500], yte[:500])


class TestTracing:
    def test_spans_events_costs(self, tmp_path):
        tr = Tracer()
        with tr.span("round", epoch=1):
            with tr.span("train"):
                tr.charge("train.samples", 300)
            tr.event("upload", client=3)
            tr.charge("ledger.ops")
        s = tr.summary()
        assert "round" in s["spans"] and "round/train" in s["spans"]
        assert s["costs"] == {"train.samples": 300.0, "ledger.ops": 1.0}
        out = tmp_path / "trace.jsonl"
        tr.dump_jsonl(str(out))
        assert out.read_text().count("\n") == 4   # 2 spans + 1 event + summary

    def test_mesh_runtime_emits_costs(self, small_data):
        from bflc_demo_tpu.client import run_federated_mesh
        shards, test_set = small_data
        tr = Tracer()
        run_federated_mesh(make_softmax_regression(), shards, test_set,
                           SMALL, rounds=2, seed=0, tracer=tr)
        costs = tr.summary()["costs"]
        assert costs["device.dispatches"] == 2
        # 3 uploads + 2 scores + 1 commit per round
        assert costs["ledger.ops"] == 2 * (3 + 2 + 1)
        assert costs["host_bytes.out"] > 0
        # the batched path charges the same ledger ops, fewer dispatches
        tr2 = Tracer()
        run_federated_mesh(make_softmax_regression(), shards, test_set,
                           SMALL, rounds=2, seed=0, rounds_per_dispatch=2,
                           tracer=tr2)
        costs2 = tr2.summary()["costs"]
        assert costs2["device.dispatches"] == 1
        assert costs2["ledger.ops"] == 2 * (3 + 2 + 1)

    def test_disabled_is_noop(self):
        tr = Tracer(enabled=False)
        with tr.span("x"):
            tr.charge("c")
        assert tr.events == [] and tr.costs == {}


class TestCheckpointResume:
    def test_roundtrip_and_resume(self, tmp_path, small_data):
        from bflc_demo_tpu.client import run_federated_mesh
        from bflc_demo_tpu.utils.checkpoint import (
            save_checkpoint, load_checkpoint, restore_params_like)
        shards, test_set = small_data
        model = make_softmax_regression()
        r1 = run_federated_mesh(model, shards, test_set, SMALL, rounds=3,
                                seed=0)
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(ckpt, r1.final_params, r1.ledger)

        flat, ledger, meta = load_checkpoint(ckpt, SMALL)
        assert meta["epoch"] == 3
        assert ledger.epoch == 3
        assert ledger.log_head() == r1.ledger_log_head
        assert sorted(ledger.committee()) == sorted(r1.ledger.committee())
        params = restore_params_like(model.init_params(0), flat)
        np.testing.assert_array_equal(np.asarray(params["W"]),
                                      np.asarray(r1.final_params["W"]))
        # resume for 2 more rounds from the restored state
        r2 = run_federated_mesh(model, shards, test_set, SMALL, rounds=2,
                                seed=1, initial_params=params,
                                resume_ledger=ledger)
        assert r2.ledger.epoch == 5
        assert all(np.isfinite(a) for _, a in r2.accuracy_history)

    def test_tampered_oplog_rejected(self, tmp_path, small_data):
        from bflc_demo_tpu.client import run_federated_mesh
        from bflc_demo_tpu.utils.checkpoint import (save_checkpoint,
                                                    load_checkpoint)
        shards, test_set = small_data
        r = run_federated_mesh(make_softmax_regression(), shards, test_set,
                               SMALL, rounds=1, seed=0)
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(ckpt, r.final_params, r.ledger)
        path = os.path.join(ckpt, "ledger.oplog")
        blob = bytearray(open(path, "rb").read())
        blob[40] ^= 0xFF          # flip a byte inside the first op
        open(path, "wb").write(bytes(blob))
        with pytest.raises(ValueError):
            load_checkpoint(ckpt, SMALL)


class TestLedgerRecoveryOps:
    def _start(self):
        led = make_ledger(SMALL, backend="python")
        for i in range(SMALL.client_num):
            led.register_node(f"0x{i:03x}")
        return led

    def test_close_round_allows_partial_scoring(self):
        led = self._start()
        # only 2 of the needed 3 updates arrive (trainer died)
        for i in (2, 3):
            led.upload_local_update(f"0x{i:03x}", b"\1" * 32, 100, 1.0, 0)
        assert led.query_all_updates() == []
        assert led.close_round() == LedgerStatus.OK
        assert len(led.query_all_updates()) == 2
        for c in led.committee():
            assert led.upload_scores(c, 0, [0.5, 0.7]) == LedgerStatus.OK
        assert led.aggregate_ready()
        assert led.commit_model(b"\2" * 32, 0) == LedgerStatus.OK
        assert led.epoch == 1
        assert led.verify_log()

    def test_close_round_guards(self):
        led = self._start()
        assert led.close_round() == LedgerStatus.NOT_READY  # no updates
        for i in (2, 3, 4):
            led.upload_local_update(f"0x{i:03x}", b"\1" * 32, 100, 1.0, 0)
        assert led.close_round() == LedgerStatus.NOT_READY  # round is full

    def test_force_aggregate_with_missing_committee_row(self):
        led = self._start()
        for i in (2, 3, 4):
            led.upload_local_update(f"0x{i:03x}", b"\1" * 32, 100, 1.0, 0)
        comm = led.committee()
        led.upload_scores(comm[0], 0, [0.9, 0.1, 0.5])  # second member dead
        assert not led.aggregate_ready()
        assert led.force_aggregate() == LedgerStatus.OK
        assert led.aggregate_ready()
        # medians over the single present row
        np.testing.assert_allclose(led.pending().medians, [0.9, 0.1, 0.5])
        assert led.commit_model(b"\2" * 32, 0) == LedgerStatus.OK

    def test_reseat_committee(self):
        """Mid-round re-election: dead committee replaced by live clients;
        scoring completes with the new (possibly smaller) committee."""
        led = self._start()
        for i in (2, 3, 4):
            led.upload_local_update(f"0x{i:03x}", b"\1" * 32, 100, 1.0, 0)
        # whole committee (0x000, 0x001) presumed dead -> reseat 5 and 6
        st = led.reseat_committee(["0x005", "0x006"])
        assert st == LedgerStatus.OK
        assert set(led.committee()) == {"0x005", "0x006"}
        assert led.upload_scores("0x005", 0, [0.9, 0.2, 0.5]) == \
            LedgerStatus.OK
        assert not led.aggregate_ready()
        assert led.upload_scores("0x006", 0, [0.8, 0.4, 0.6]) == \
            LedgerStatus.OK
        assert led.aggregate_ready()      # fires at the NEW committee size
        assert led.commit_model(b"\2" * 32, 0) == LedgerStatus.OK

    def test_reseat_guards(self):
        led = self._start()
        assert led.reseat_committee([]) == LedgerStatus.BAD_ARG
        assert led.reseat_committee(["0xdead"]) == LedgerStatus.BAD_ARG
        assert led.reseat_committee(
            [f"0x{i:03x}" for i in range(3)]) == LedgerStatus.BAD_ARG  # > comm

    def test_recovery_ops_replay(self):
        led = self._start()
        for i in (2, 3):
            led.upload_local_update(f"0x{i:03x}", b"\1" * 32, 100, 1.0, 0)
        led.close_round()
        led.upload_scores(led.committee()[0], 0, [0.5, 0.7])
        led.force_aggregate()
        led.commit_model(b"\4" * 32, 0)
        replica = make_ledger(SMALL, backend="python")
        for i in range(led.log_size()):
            assert replica.apply_op(led.log_op(i)) == LedgerStatus.OK
        assert replica.log_head() == led.log_head()
        assert replica.epoch == 1


class TestThreadedRuntime:
    def test_clean_concurrent_run(self, small_data):
        from bflc_demo_tpu.client.threaded import ThreadedFederation
        shards, test_set = small_data
        fed = ThreadedFederation(make_softmax_regression(), shards, test_set,
                                 SMALL, stall_timeout_s=3.0)
        res = fed.run(rounds=3, timeout_s=120)
        assert res.rounds_completed == 3
        assert res.ledger.verify_log()
        # epochs strictly monotonic in the loss history
        epochs = [e for e, _ in res.loss_history]
        assert epochs == sorted(set(epochs))

    def test_trainer_crashes_recovered(self, small_data):
        """Kill most trainers at epoch 1: rounds keep completing via
        close_round (the reference would stall, SURVEY.md §5)."""
        from bflc_demo_tpu.client.threaded import ThreadedFederation
        shards, test_set = small_data
        crash = {i: 1 for i in range(2, 7)}     # 5 of 8 clients die
        fed = ThreadedFederation(make_softmax_regression(), shards, test_set,
                                 SMALL, crash_at=crash, stall_timeout_s=0.75)
        res = fed.run(rounds=3, timeout_s=180)
        assert res.rounds_completed == 3
        # which recovery fires depends on whether the dead five include the
        # round-1 committee (reseat) or only trainers (close_round) — either
        # way the run must have recovered rather than stalled
        assert fed.recoveries, "expected at least one recovery action"

    def test_committee_crash_recovered(self, small_data):
        """Kill a committee member mid-protocol: force_aggregate unblocks."""
        from bflc_demo_tpu.client.threaded import ThreadedFederation
        shards, test_set = small_data
        # genesis committee = clients 0,1 (registration order); kill 1 at ep 0
        fed = ThreadedFederation(make_softmax_regression(), shards, test_set,
                                 SMALL, crash_at={1: 0}, stall_timeout_s=0.75)
        res = fed.run(rounds=2, timeout_s=180)
        assert res.rounds_completed == 2
        assert any(r.startswith("force_aggregate") for r in fed.recoveries), \
            fed.recoveries

    def test_whole_committee_dead_reseated(self, small_data):
        """Kill the ENTIRE genesis committee before it can score: the
        detector reseats live clients mid-round and training continues —
        the exact case that deadlocks the reference forever (SURVEY.md §5:
        'a dead committee member deadlocks the round; nothing re-elects
        mid-round')."""
        from bflc_demo_tpu.client.threaded import ThreadedFederation
        shards, test_set = small_data
        fed = ThreadedFederation(make_softmax_regression(), shards, test_set,
                                 SMALL, crash_at={0: 0, 1: 0},
                                 stall_timeout_s=0.75)
        res = fed.run(rounds=2, timeout_s=180)
        assert res.rounds_completed == 2
        assert any(r.startswith("reseat") for r in fed.recoveries), \
            fed.recoveries
        assert res.ledger.verify_log()


class TestConcurrencyInvariants:
    @pytest.mark.parametrize("backend", ["python", "native"])
    def test_upload_storm_respects_guards(self, backend):
        """64 threads racing uploads: exactly needed_update_count accepted,
        no duplicate slots, log intact — the protocol invariants of
        .cpp:225-244 under true concurrency (the reference gets this from
        PBFT ordering; we get it from the ledger serialization point)."""
        import threading
        from bflc_demo_tpu.client.threaded import LockingLedger
        from bflc_demo_tpu.ledger import bindings
        if backend == "native" and not bindings.native_available():
            pytest.skip("native ledger unavailable")
        cfg = ProtocolConfig(client_num=64, comm_count=4, aggregate_count=6,
                             needed_update_count=10)
        led = LockingLedger(make_ledger(cfg, backend=backend))
        for i in range(64):
            led.register_node(f"0x{i:03x}")
        results = {}

        def upload(i):
            st = led.upload_local_update(f"0x{i:03x}", bytes([i]) * 32,
                                         100 + i, 1.0, 0)
            results[i] = st
            # racing duplicate from the same sender
            results[(i, "dup")] = led.upload_local_update(
                f"0x{i:03x}", bytes([i]) * 32, 100 + i, 1.0, 0)

        threads = [threading.Thread(target=upload, args=(i,))
                   for i in range(4, 64)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        accepted = [i for i in range(4, 64)
                    if results[i] == LedgerStatus.OK]
        assert len(accepted) == cfg.needed_update_count
        assert led.update_count == cfg.needed_update_count
        # a sender's second call never succeeds (dup or cap)
        assert all(results[(i, "dup")] != LedgerStatus.OK
                   for i in range(4, 64))
        # accepted senders' dups were rejected as DUPLICATE specifically
        assert all(results[(i, "dup")] == LedgerStatus.DUPLICATE
                   for i in accepted)
        assert led.verify_log()


class TestFlags:
    def test_parse_defaults(self):
        opts, cfg = parse_args([])
        assert opts.config == "config1" and opts.runtime == "mesh"
        assert cfg is None        # no overrides -> preset default

    def test_protocol_overrides(self):
        opts, cfg = parse_args(["--config", "config2", "--rounds", "3",
                                "--comm-count", "2", "--client-num", "10",
                                "--needed-update-count", "5",
                                "--aggregate-count", "3"])
        assert opts.rounds == 3
        assert cfg.comm_count == 2 and cfg.client_num == 10

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("BFLC_COMM_COUNT", "3")
        monkeypatch.setenv("BFLC_LEARNING_RATE", "0.01")
        cfg = protocol_from_env()
        assert cfg.comm_count == 3
        assert abs(cfg.learning_rate - 0.01) < 1e-12

    def test_invalid_override_rejected(self):
        with pytest.raises(ValueError):
            parse_args(["--comm-count", "50"])


class TestCompileCache:
    def test_enable_sets_jax_config(self, tmp_path, monkeypatch):
        import jax
        from bflc_demo_tpu.utils.compile_cache import enable_persistent_cache
        before = jax.config.jax_compilation_cache_dir
        monkeypatch.setenv("BFLC_COMPILE_CACHE", str(tmp_path / "cc"))
        try:
            assert enable_persistent_cache() == str(tmp_path / "cc")
            assert jax.config.jax_compilation_cache_dir == \
                str(tmp_path / "cc")
        finally:
            # jax.config is process-global: restore so later tests never
            # write cache artifacts into this test's tmp dir
            jax.config.update("jax_compilation_cache_dir", before)

    def test_disabled_via_env(self, monkeypatch):
        from bflc_demo_tpu.utils.compile_cache import enable_persistent_cache
        monkeypatch.setenv("BFLC_COMPILE_CACHE", "0")
        assert enable_persistent_cache() == ""


def test_plot_run_writes_png(tmp_path):
    """Run-evidence plot: renders a full SimulationResult-shaped object
    headlessly and writes a real PNG."""
    from types import SimpleNamespace
    from bflc_demo_tpu.eval.plot import plot_run
    res = SimpleNamespace(
        accuracy_history=[(0, 0.8), (1, 0.9), (2, 0.93)],
        loss_history=[(0, 55.0), (1, 6.2), (2, 5.9)],
        round_times_s=[0.5, 0.2, 0.2])
    out = plot_run(res, str(tmp_path / "ev.png"), title="t")
    with open(out, "rb") as f:
        assert f.read(8) == b"\x89PNG\r\n\x1a\n"


class TestMeshScoreAttestation:
    """Round 7: score attestation is default-on across the mesh family
    when wallets exist, with an explicit attest_scores=False opt-out —
    the trust feature is no longer a runtime choice (PARITY divergence
    #1 closed by default)."""

    def test_default_on_with_wallets_and_opt_out(self, small_data):
        from bflc_demo_tpu.client import run_federated_mesh
        from bflc_demo_tpu.comm.identity import provision_wallets
        shards, test_set = small_data
        wallets, _ = provision_wallets(SMALL.client_num, b"attest-aux-1")
        res = run_federated_mesh(make_softmax_regression(), shards,
                                 test_set, SMALL, rounds=2, seed=0,
                                 attest_wallets=wallets)
        # wallets present, nothing else asked for: attestation is ON and
        # every round's committee rows carry verifying signatures
        assert res.attest_log and sorted(res.attest_log) == [0, 1]
        led = res.ledger
        for epoch, sigs in res.attest_log.items():
            assert len(sigs) == SMALL.comm_count
            for addr, sig_hex in sigs.items():
                cid = int(addr, 16)
                w = wallets[cid]
                # signature binds (kind, sender, epoch) — re-verifiable
                # by any holder of the round inputs; here we check the
                # identity binding round-trips
                assert addr == f"0x{cid:040x}"
                assert len(bytes.fromhex(sig_hex)) == 64
        assert led is not None
        # explicit opt-out: no attestation work, no log
        res2 = run_federated_mesh(make_softmax_regression(), shards,
                                  test_set, SMALL, rounds=2, seed=0,
                                  attest_wallets=wallets,
                                  attest_scores=False)
        assert res2.attest_log is None
        # identical training outcome either way (attestation is evidence,
        # not arithmetic)
        assert res.accuracy_history == res2.accuracy_history
        # no wallets at all: default stays off...
        res3 = run_federated_mesh(make_softmax_regression(), shards,
                                  test_set, SMALL, rounds=1, seed=0)
        assert res3.attest_log is None
        # ...but an explicit request without wallets must error, never
        # silently drop the trust feature
        with pytest.raises(ValueError, match="wallets"):
            run_federated_mesh(make_softmax_regression(), shards,
                               test_set, SMALL, rounds=1, seed=0,
                               attest_scores=True)

    def test_batched_dispatch_attests_every_replayed_round(self,
                                                           small_data):
        from bflc_demo_tpu.client import run_federated_mesh
        from bflc_demo_tpu.comm.identity import provision_wallets
        shards, test_set = small_data
        wallets, _ = provision_wallets(SMALL.client_num, b"attest-aux-2")
        res = run_federated_mesh(make_softmax_regression(), shards,
                                 test_set, SMALL, rounds=2, seed=0,
                                 rounds_per_dispatch=2,
                                 attest_wallets=wallets)
        assert res.attest_log and sorted(res.attest_log) == [0, 1]
        assert all(len(s) == SMALL.comm_count
                   for s in res.attest_log.values())
