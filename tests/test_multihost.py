"""Two-process multihost drill: comm/multihost.py exercised for real.

The reference's multi-machine story is one FISCO node per host under PBFT
(README.md:162-183).  The TPU-native split is: data plane = jax.distributed
collectives over every host's devices; control plane = one ledger writer
host, others replaying the op stream (comm/multihost docstring).  This test
runs BOTH planes across two real OS processes on loopback:

- each process calls `multihost.initialize` against a shared coordinator
  (real jax.distributed bring-up, CPU backend, Gloo transport);
- a psum over `multihost.global_mesh` crosses the process boundary and both
  sides must see the identical global sum (the DCN-collective stand-in);
- process 0 (`is_ledger_writer`) serves the networked ledger; process 1
  live-replicates the op stream and proves chained head-digest equality.
"""

import contextlib
import multiprocessing as mp
import os
import socket

import numpy as np
import pytest

from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)


@contextlib.contextmanager
def _cpu_spawn_env():
    saved = {k: os.environ.get(k)
             for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS", "XLA_FLAGS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _host_proc(pid: int, nprocs: int, coord_port: int, cfg_kw: dict,
               srv_port_q, done_ev, result_q) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    try:
        import jax
        import jax.numpy as jnp
        from bflc_demo_tpu.utils.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        jax.config.update("jax_platforms", "cpu")
        from bflc_demo_tpu.comm import multihost
        from bflc_demo_tpu.comm.ledger_service import (LedgerServer,
                                                       CoordinatorClient,
                                                       replicate)
        from bflc_demo_tpu.utils.serialization import pack_pytree

        cfg = ProtocolConfig(**cfg_kw)
        assert multihost.initialize(f"localhost:{coord_port}", nprocs, pid)
        assert jax.process_index() == pid
        writer = multihost.is_ledger_writer()
        assert writer == (pid == 0)

        # ---- data plane: one collective spanning both processes
        mesh = multihost.global_mesh(("clients",))
        n_global = len(jax.devices())
        n_local = len(jax.local_devices())
        fn = jax.jit(shard_map(lambda v: jax.lax.psum(v, "clients"),
                               mesh=mesh, in_specs=P("clients"),
                               out_specs=P(), check_vma=False))
        local = np.full((n_local,), float(pid + 1), np.float32)
        arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("clients")), local, (n_global,))
        got = float(np.asarray(fn(arr))[0])
        # 2 local devices per process contributing (pid+1) each
        want = float(sum(2 * (i + 1) for i in range(nprocs)))

        # ---- control plane: writer serves ops, replica replays + verifies
        if writer:
            blob = pack_pytree({"W": np.zeros((5, 2), np.float32)})
            server = LedgerServer(cfg, blob, require_auth=False,
                                  ledger_backend="python",
                                  stall_timeout_s=60.0)
            server.start()
            srv_port_q.put(server.port)
            c = CoordinatorClient(server.host, server.port)
            for i in range(cfg.client_num):
                assert c.request("register", addr=f"0x{i:040x}")["ok"]
            info = c.request("info")
            c.close()
            if not done_ev.wait(timeout=120):
                raise TimeoutError("replica never finished")
            server.close()
            result_q.put({"pid": pid, "psum": got, "want": want,
                          "log_head": info["log_head"],
                          "log_size": info["log_size"]})
        else:
            port = srv_port_q.get(timeout=120)
            c = CoordinatorClient("127.0.0.1", port)
            # wait until the writer has registered the full population
            while True:
                info = c.request("info")
                if info["num_registered"] == cfg.client_num:
                    break
                c.request("wait", log_size=info["log_size"], timeout_s=5.0)
            c.close()
            replica = replicate("127.0.0.1", port, cfg,
                                ledger_backend="python",
                                until_ops=info["log_size"], timeout_s=60.0)
            done_ev.set()
            result_q.put({"pid": pid, "psum": got, "want": want,
                          "log_head": replica.log_head().hex(),
                          "log_size": replica.log_size()})
    except BaseException as e:          # noqa: BLE001 — report, don't hang
        done_ev.set()
        result_q.put({"pid": pid, "error": f"{type(e).__name__}: {e}"})
        raise


@pytest.mark.slow
def test_two_process_multihost_drill():
    import dataclasses
    cfg_kw = {f.name: getattr(CFG, f.name)
              for f in dataclasses.fields(CFG)}
    ctx = mp.get_context("spawn")
    srv_port_q = ctx.Queue()
    result_q = ctx.Queue()
    done_ev = ctx.Event()
    coord_port = _free_port()
    with _cpu_spawn_env():
        procs = [ctx.Process(target=_host_proc,
                             args=(pid, 2, coord_port, cfg_kw, srv_port_q,
                                   done_ev, result_q), daemon=True)
                 for pid in range(2)]
        for p in procs:
            p.start()
    results = {}
    try:
        for _ in range(2):
            r = result_q.get(timeout=240)
            results[r["pid"]] = r
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    for pid in (0, 1):
        assert "error" not in results[pid], results[pid]
        # the cross-process psum saw every host's contribution
        assert results[pid]["psum"] == results[pid]["want"]
    # replica (pid 1) replayed the writer's stream to an identical head
    assert results[0]["log_size"] == results[1]["log_size"] > 0
    assert results[0]["log_head"] == results[1]["log_head"]
