"""Control-plane TLS: encrypted client<->coordinator transport.

The reference's Channel protocol is TLS with provisioned certs
(README.md:240-260); these tests prove the equivalent here — the full
protocol works over TLS, a plaintext client is rejected at the transport,
and a client refusing the CA fails verification.
"""

import hashlib
import socket
import struct

import numpy as np
import pytest

# cert provisioning no longer needs the `cryptography` wheel: without it,
# provision_tls falls back to the pure-Python Ed25519 x509 path
# (comm.x509mini) — this suite runs everywhere the identity layer does
# (the former ROADMAP skip is closed)

from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                               LedgerServer, replicate)
from bflc_demo_tpu.comm.tls import (client_context, provision_tls,
                                    server_context)
from bflc_demo_tpu.comm.wire import WireError, send_msg, recv_msg
from bflc_demo_tpu.protocol import ProtocolConfig
from bflc_demo_tpu.utils.serialization import pack_pytree

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)


def _init_blob():
    return pack_pytree({"W": np.zeros((5, 2), np.float32),
                        "b": np.zeros((2,), np.float32)})


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tls"))
    provision_tls(d)
    return d


@pytest.fixture
def tls_server(certs):
    srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                       stall_timeout_s=60.0, ledger_backend="python",
                       tls=server_context(certs))
    srv.start()
    yield srv
    srv.close()


class TestTLS:
    def test_provision_idempotent(self, certs):
        import os
        paths = provision_tls(certs)
        mtimes = [os.path.getmtime(p) for p in paths]
        assert provision_tls(certs) == paths
        assert [os.path.getmtime(p) for p in paths] == mtimes

    def test_full_protocol_over_tls(self, tls_server, certs):
        """Register the fleet, drive a full round to an aggregated commit,
        and replicate the log — every byte TLS-framed."""
        tls = client_context(certs)
        c = CoordinatorClient(tls_server.host, tls_server.port, tls=tls)
        import ssl
        assert isinstance(c.sock, ssl.SSLSocket)
        addrs = [f"0x{i:040x}" for i in range(CFG.client_num)]
        for a in addrs:
            assert c.request("register", addr=a)["ok"]
        committee = c.request("committee")["committee"]
        trainers = [a for a in addrs if a not in committee]
        for i, a in enumerate(trainers[: CFG.needed_update_count]):
            blob = pack_pytree({"W": np.full((5, 2), i + 1.0, np.float32),
                                "b": np.zeros((2,), np.float32)})
            digest = hashlib.sha256(blob).digest()
            r = c.request("upload", addr=a, blob=blob.hex(),
                          hash=digest.hex(), n=10, cost=1.0, epoch=0)
            assert r["ok"], r
        for j, a in enumerate(committee):
            r = c.request("scores", addr=a, epoch=0,
                          scores=[0.5 + 0.01 * u for u in range(
                              CFG.needed_update_count)])
            assert r["ok"], r
        info = c.request("info")
        assert info["epoch"] == 1           # aggregated + committed
        # live replication over the same TLS transport
        replica = replicate(tls_server.host, tls_server.port, CFG,
                            ledger_backend="python",
                            until_ops=info["log_size"], timeout_s=30.0,
                            tls=tls)
        assert replica.log_head().hex() == info["log_head"]
        c.close()

    def test_plaintext_client_rejected(self, tls_server):
        """A non-TLS client against the TLS server must get nothing back:
        the server kills the connection at the failed handshake."""
        sock = socket.create_connection((tls_server.host, tls_server.port),
                                        timeout=5.0)
        sock.settimeout(5.0)
        try:
            send_msg(sock, {"method": "info"})
            with pytest.raises((WireError, ConnectionError, OSError)):
                reply = recv_msg(sock)
                if reply is None:           # clean close also = rejection
                    raise ConnectionError("closed by server")
        finally:
            sock.close()

    @pytest.mark.slow
    def test_process_federation_over_tls(self, tmp_path):
        """The reference's deployment shape with its transport property:
        OS-process clients, every control-plane byte TLS-encrypted."""
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        from bflc_demo_tpu.data import load_occupancy, iid_shards

        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1200], ytr[:1200], CFG.client_num)
        res = run_federated_processes(
            "make_softmax_regression", shards, (xte[:400], yte[:400]), CFG,
            rounds=3, stall_timeout_s=20.0, timeout_s=420.0, replicas=1,
            tls_dir=str(tmp_path / "certs"))
        assert res.rounds_completed >= 3
        assert res.best_accuracy() > 0.80
        assert res.replica_report["ok"]

    def test_wrong_ca_rejected(self, tls_server, tmp_path):
        """A client that trusts a DIFFERENT CA fails verification."""
        import ssl
        other = str(tmp_path / "other")
        provision_tls(other)
        with pytest.raises(ssl.SSLError):
            CoordinatorClient(tls_server.host, tls_server.port,
                              tls=client_context(other))

    def test_wrong_hostname_rejected(self, tmp_path):
        """Server identity is the SAN match, not CA membership (VERDICT r4
        weak #2a): a cert validly signed by the trusted CA but provisioned
        for a DIFFERENT host must fail the client handshake."""
        import ssl
        d = str(tmp_path / "otherhost")
        provision_tls(d, common_name="db.internal.example",
                      include_loopback=False)
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           tls=server_context(d))
        srv.start()
        try:
            with pytest.raises(ssl.SSLCertVerificationError):
                CoordinatorClient(srv.host, srv.port,
                                  tls=client_context(d))
        finally:
            srv.close()
