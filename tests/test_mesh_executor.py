"""The composed deployment: OS-process clients + socket control plane +
device-mesh data plane (VERDICT round-2 weak #6 closed).

Every round executes as ONE SPMD program (make_sharded_protocol_round) on
the executor's mesh while real client processes register, stage shards with
signed requests, and verify committed models over the socket — the
reference's deployment shape (main.py:343-358) running the BASELINE
north-star data plane.
"""

import numpy as np
import pytest

from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)


@pytest.mark.slow
class TestMeshExecutorFederation:
    def test_process_clients_mesh_rounds(self):
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_mesh_processes
        from bflc_demo_tpu.data import load_occupancy, iid_shards

        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1500], ytr[:1500], CFG.client_num)
        res = run_federated_mesh_processes(
            "make_softmax_regression", shards, (xte[:500], yte[:500]), CFG,
            rounds=3, n_virtual_devices=3, timeout_s=420.0)
        assert res.rounds_completed >= 3
        assert res.best_accuracy() > 0.80, res.accuracy_history
        # the ledger audited every mesh round: registrations + per round
        # (uploads + scores + commit)
        assert res.ledger_log_size == CFG.client_num + 3 * (
            CFG.needed_update_count + CFG.comm_count + 1)


class TestExecutorServerInThread:
    def test_stage_validation(self):
        """Unsigned / malformed staging is rejected at the boundary."""
        from bflc_demo_tpu.comm.executor_service import MeshExecutorServer
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        from bflc_demo_tpu.utils.serialization import pack_entries

        srv = MeshExecutorServer(CFG, "make_softmax_regression",
                                 rounds=1, require_auth=False,
                                 stall_timeout_s=600.0,
                                 ledger_backend="python")
        srv.start()
        try:
            c = CoordinatorClient(srv.host, srv.port)
            xb = pack_entries({"x": np.zeros((10, 5), np.float32)})
            yb = pack_entries({"y": np.zeros((9,), np.int32)})   # mismatch
            r = c.request("stage", addr="0x" + "0" * 40, x=xb.hex(),
                          y=yb.hex())
            assert not r["ok"] and r["status"] == "BAD_ARG"
            r = c.request("stage", addr="0x" + "0" * 40, x="zz", y="zz")
            assert not r["ok"]
            yb2 = pack_entries({"y": np.zeros((10,), np.int32)})
            r = c.request("stage", addr="0x" + "0" * 40, x=xb.hex(),
                          y=yb2.hex())
            assert r["ok"] and r["staged"] == 1
            assert c.request("progress")["rounds_done"] == 0
            c.close()
        finally:
            srv.close()


class TestScoreAttestation:
    """Score-attestation trust locality (VERDICT r4 missing #2): committee
    members re-score the round's candidates on their OWN shard and sign
    their row before the ledger accepts the round.  A coordinator that
    fabricates a row gets no signature and the round aborts."""

    def _setup(self, server_cls, attest_timeout_s=30.0):
        import hashlib as hl

        from bflc_demo_tpu.comm.identity import provision_wallets, _op_bytes
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        from bflc_demo_tpu.utils.serialization import pack_entries

        wallets, directory = provision_wallets(CFG.client_num,
                                               b"attest-master-0001")
        srv = server_cls(CFG, "make_softmax_regression", rounds=1,
                         attest_scores=True,
                         attest_timeout_s=attest_timeout_s,
                         directory=directory, stall_timeout_s=600.0,
                         ledger_backend="python")
        srv.start()
        rng = np.random.default_rng(7)
        shards = {}
        c = CoordinatorClient(srv.host, srv.port, timeout_s=30.0)
        for i, w in enumerate(wallets):
            size = 40 if i == 0 else 32     # ragged: force cyclic padding
            x = rng.standard_normal((size, 5)).astype(np.float32)
            y = rng.integers(0, 2, (size,)).astype(np.int32)
            shards[w.address] = (x, y)
            r = c.request("register", addr=w.address,
                          pubkey=w.public_bytes.hex(),
                          tag=w.sign(_op_bytes("register", w.address, 0,
                                               b"")).hex())
            assert r["ok"], r
        for w in wallets:
            x, y = shards[w.address]
            xb = pack_entries({"x": x})
            yb = pack_entries({"y": y})
            payload = hl.sha256(xb).digest() + hl.sha256(yb).digest()
            r = c.request("stage", addr=w.address, x=xb.hex(), y=yb.hex(),
                          tag=w.sign(_op_bytes("stage", w.address, 0,
                                               payload)).hex())
            assert r["ok"], r
        return srv, c, wallets, shards

    def test_attested_round_commits_and_logs_signatures(self):
        import time as _t

        from bflc_demo_tpu.client.process_runtime import attest_score_row
        from bflc_demo_tpu.comm.executor_service import MeshExecutorServer
        from bflc_demo_tpu.models import make_softmax_regression

        model = make_softmax_regression()
        template = model.init_params(0)
        srv, c, wallets, shards = self._setup(MeshExecutorServer)
        try:
            deadline = _t.monotonic() + 60
            attested = 0
            while _t.monotonic() < deadline:
                pr = c.request("progress")
                assert not pr.get("error"), pr
                if pr["rounds_done"] >= 1:
                    break
                for w in wallets:
                    pa = c.request("round_pending", addr=w.address)
                    if pa.get("epoch") is not None:
                        x, y = shards[w.address]
                        assert attest_score_row(c, w, model, template,
                                                CFG, x, y, pa)
                        attested += 1
                _t.sleep(0.1)
            assert c.request("progress")["rounds_done"] == 1
            assert attested == CFG.comm_count
            # the signed rows are recorded per epoch, one per member
            assert len(srv.attest_log[0]) == CFG.comm_count
        finally:
            c.close()
            srv.close()

    def test_tampered_row_refused_and_round_aborts(self):
        """The coordinator perturbs one committee row after the mesh
        computed it: the member's local recomputation disagrees, it
        REFUSES to sign, and the round never reaches the ledger."""
        import time as _t

        import pytest as _pytest

        from bflc_demo_tpu.client.process_runtime import attest_score_row
        from bflc_demo_tpu.comm.executor_service import MeshExecutorServer
        from bflc_demo_tpu.models import make_softmax_regression

        class TamperingExecutor(MeshExecutorServer):
            def _collect_attestations(self, epoch, addrs, uploader_ids,
                                      committee_ids, delta_fps, score_rows,
                                      cand_deltas, s_pad):
                rows = np.array(score_rows, copy=True)
                rows[committee_ids[0], uploader_ids[0]] += 0.25
                super()._collect_attestations(
                    epoch, addrs, uploader_ids, committee_ids, delta_fps,
                    rows, cand_deltas, s_pad)

        model = make_softmax_regression()
        template = model.init_params(0)
        srv, c, wallets, shards = self._setup(TamperingExecutor,
                                              attest_timeout_s=4.0)
        try:
            refused = 0
            deadline = _t.monotonic() + 45
            while _t.monotonic() < deadline:
                pr = c.request("progress")
                if pr.get("error"):
                    break
                for w in wallets:
                    pa = c.request("round_pending", addr=w.address)
                    if pa.get("epoch") is None:
                        continue
                    x, y = shards[w.address]
                    try:
                        attest_score_row(c, w, model, template, CFG, x, y,
                                         pa)
                    except RuntimeError as e:
                        assert "does not match" in str(e)
                        refused += 1
                _t.sleep(0.1)
            err = c.request("progress").get("error") or ""
            assert "did not attest" in err, err
            assert refused >= 1
            assert c.request("progress")["rounds_done"] == 0
            assert c.request("info")["epoch"] == 0   # nothing committed
        finally:
            c.close()
            srv.close()


@pytest.mark.slow
class TestMeshExecutorTLS:
    def test_mesh_executor_over_tls(self, tmp_path):
        """The composed deployment fully TLS-encrypted: staged raw shards,
        model fetches, and attestation traffic all ride the encrypted
        control plane (the reference's Channel-TLS property on the
        mesh-executor shape)."""
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_mesh_processes
        from bflc_demo_tpu.data import load_occupancy, iid_shards

        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1500], ytr[:1500], CFG.client_num)
        res = run_federated_mesh_processes(
            "make_softmax_regression", shards, (xte[:500], yte[:500]), CFG,
            rounds=3, n_virtual_devices=3, timeout_s=420.0,
            attest_scores=True, tls_dir=str(tmp_path / "certs"))
        assert res.rounds_completed >= 3
        assert res.best_accuracy() > 0.80, res.accuracy_history
