"""The composed deployment: OS-process clients + socket control plane +
device-mesh data plane (VERDICT round-2 weak #6 closed).

Every round executes as ONE SPMD program (make_sharded_protocol_round) on
the executor's mesh while real client processes register, stage shards with
signed requests, and verify committed models over the socket — the
reference's deployment shape (main.py:343-358) running the BASELINE
north-star data plane.
"""

import numpy as np
import pytest

from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)


@pytest.mark.slow
class TestMeshExecutorFederation:
    def test_process_clients_mesh_rounds(self):
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_mesh_processes
        from bflc_demo_tpu.data import load_occupancy, iid_shards

        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1500], ytr[:1500], CFG.client_num)
        res = run_federated_mesh_processes(
            "make_softmax_regression", shards, (xte[:500], yte[:500]), CFG,
            rounds=3, n_virtual_devices=3, timeout_s=420.0)
        assert res.rounds_completed >= 3
        assert res.best_accuracy() > 0.80, res.accuracy_history
        # the ledger audited every mesh round: registrations + per round
        # (uploads + scores + commit)
        assert res.ledger_log_size == CFG.client_num + 3 * (
            CFG.needed_update_count + CFG.comm_count + 1)


class TestExecutorServerInThread:
    def test_stage_validation(self):
        """Unsigned / malformed staging is rejected at the boundary."""
        from bflc_demo_tpu.comm.executor_service import MeshExecutorServer
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        from bflc_demo_tpu.utils.serialization import pack_entries

        srv = MeshExecutorServer(CFG, "make_softmax_regression",
                                 rounds=1, require_auth=False,
                                 stall_timeout_s=600.0,
                                 ledger_backend="python")
        srv.start()
        try:
            c = CoordinatorClient(srv.host, srv.port)
            xb = pack_entries({"x": np.zeros((10, 5), np.float32)})
            yb = pack_entries({"y": np.zeros((9,), np.int32)})   # mismatch
            r = c.request("stage", addr="0x" + "0" * 40, x=xb.hex(),
                          y=yb.hex())
            assert not r["ok"] and r["status"] == "BAD_ARG"
            r = c.request("stage", addr="0x" + "0" * 40, x="zz", y="zz")
            assert not r["ok"]
            yb2 = pack_entries({"y": np.zeros((10,), np.int32)})
            r = c.request("stage", addr="0x" + "0" * 40, x=xb.hex(),
                          y=yb2.hex())
            assert r["ok"] and r["staged"] == 1
            assert c.request("progress")["rounds_done"] == 0
            c.close()
        finally:
            srv.close()
