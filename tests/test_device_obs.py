"""Device-plane observability (bflc_demo_tpu/obs/device.py; ISSUE 19).

The properties under test:

- compile & cost attribution: the meshagg engine's geometry-keyed
  program cache reports fresh compiles / cache hits per program family,
  and a forced geometry change produces exactly the expected fresh
  events; static-argnames jits are signature-tracked (observe_jit);
- certified bytes are IDENTICAL with the plane armed and disarmed
  (`BFLC_DEVICE_OBS=0` legacy pin) — the device plane changes no trust
  and no bytes;
- the recompile-storm detector WARNs on one post-warmup fresh compile,
  escalates a sustained streak to CRIT, and raises ZERO false verdicts
  on the steady-state zero-compile loop (including its own cold start);
- memory watermarks fall back to the host chain (RSS/getrusage/
  tracemalloc) on CPU and honor BFLC_DEVICE_MEM_CEILING_BYTES;
- xprof capture windows are entirely inert when unarmed;
- the device jsonl sink round-trips through the shared loader, joins
  the round timeline (scrape differencing with a warmup-None guard),
  and feeds chaos_soak's --fail-on-recompile-storm operator gate and
  check_reduction_spec's steady-state recompile gate.
"""

import json
import os
import sys

import numpy as np
import pytest

from bflc_demo_tpu.obs import device as obs_device
from bflc_demo_tpu.obs import flight as obs_flight
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs.timeline import (DEVICE_SLO_WARMUP_ROUNDS,
                                        RoundTimeline)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))


def _tool(name):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@pytest.fixture
def armed(tmp_path):
    """Armed device plane: registry on, no legacy pin, mirrors reset,
    sink pointed at tmp_path.  Everything restored on exit."""
    saved_enabled = obs_metrics.REGISTRY.enabled
    saved_role = obs_metrics.REGISTRY.role
    saved_pin = os.environ.pop("BFLC_DEVICE_OBS", None)
    saved_dir = obs_device._SINK["dir"]
    saved_xprof = obs_device.XPROF
    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "writer"
    obs_device.reset_state()
    obs_device._SINK["dir"] = str(tmp_path)
    try:
        yield tmp_path
    finally:
        obs_metrics.REGISTRY.enabled = saved_enabled
        obs_metrics.REGISTRY.role = saved_role
        obs_device._SINK["dir"] = saved_dir
        obs_device.XPROF = saved_xprof
        if saved_pin is not None:
            os.environ["BFLC_DEVICE_OBS"] = saved_pin
        obs_device.reset_state()


def _scenario(p, n=3, seed=0):
    """One tiny fixed reduction scenario with a distinctive param count
    (the engine program cache is keyed on (n, p) and shared across the
    test session — unusual primes guarantee fresh geometries)."""
    rng = np.random.default_rng(seed)
    g = {"/w": rng.standard_normal(p).astype(np.float32)}
    deltas = [{"/w": rng.standard_normal(p).astype(np.float32)}
              for _ in range(n)]
    weights = [float(rng.integers(1, 50)) for _ in range(n)]
    selected = list(range(n))
    return g, deltas, weights, selected


# ------------------------------------------------ compile attribution
class TestCompileAttribution:
    def test_engine_geometry_change_records_fresh_compiles(self, armed):
        """A new (n, p) geometry is a cache miss + fresh compile events
        for family 'reduce'; the SAME geometry again is a cache hit and
        zero fresh compiles — the steady-state invariant the storm
        detector pages on."""
        from bflc_demo_tpu.meshagg import spec
        from bflc_demo_tpu.meshagg.engine import ENGINE

        def _run(p):
            g, deltas, weights, selected = _scenario(p)
            w = spec.merge_weight_vector(weights, selected, len(deltas))
            ENGINE.weighted_sum(sorted(g), deltas, w,
                                max(float(w.sum()), 1e-12),
                                force_leg="mesh")

        def _fam():
            return obs_device.report()["families"].get("reduce", {})

        _run(7919)
        after_first = _fam()
        assert after_first.get("compiles", 0) >= 1
        assert after_first.get("cache_misses", 0) == 1
        assert after_first.get("compile_seconds", 0) > 0
        _run(7919)                       # same geometry: hit, no compile
        after_repeat = _fam()
        assert after_repeat["compiles"] == after_first["compiles"]
        assert after_repeat["cache_hits"] == 1
        _run(7927)                       # forced recompile
        after_change = _fam()
        assert after_change["compiles"] > after_first["compiles"]
        assert after_change["cache_misses"] == 2
        # execute time is observed on every call, not just fresh ones
        assert after_change["execute_calls"] >= 3

    def test_observe_jit_signature_tracking(self, armed):
        """A static-argnames-style jit records one ESTIMATED compile
        event per new abstract signature and execute time on every
        call."""
        import jax
        import jax.numpy as jnp

        fn = obs_device.observe_jit(jax.jit(lambda x: x * 2.0),
                                    "train_step")
        fn(jnp.ones((4,), jnp.float32))
        fn(jnp.ones((4,), jnp.float32))      # known signature
        fn(jnp.ones((5,), jnp.float32))      # new shape -> compile
        fam = obs_device.report()["families"]["train_step"]
        assert fam["compiles"] == 2
        assert fam["execute_calls"] == 3
        recs = obs_device.load_device_records(str(armed))
        est = [r for r in recs if r["type"] == "device_compile"
               and r["family"] == "train_step"]
        assert len(est) == 2 and all(r["estimated"] for r in est)

    def test_cost_analysis_unavailable_is_counted(self, armed):
        """The shared helper never bare-swallows: a raising
        cost_analysis yields zeros AND a counted unavailability
        (the eval/mfu.py satellite's contract)."""
        class _Bad:
            def cost_analysis(self):
                raise RuntimeError("no backend")

        class _Listy:
            def cost_analysis(self):
                return [{"flops": 5.0, "bytes accessed": 7.0}]

        assert obs_device.cost_analysis_stats(_Bad(), "mfu") == {
            "flops": 0.0, "bytes": 0.0}
        assert obs_device.report()["cost_analysis_unavailable"] == 1
        assert obs_device.cost_analysis_stats(_Listy(), "mfu") == {
            "flops": 5.0, "bytes": 7.0}
        assert obs_device.report()["cost_analysis_unavailable"] == 1

    def test_disarmed_plane_records_nothing(self, armed):
        os.environ["BFLC_DEVICE_OBS"] = "0"
        assert obs_device.device_legacy()
        assert not obs_device.device_armed()
        obs_device.record_compile("reduce", 1.0)
        obs_device.record_cache("reduce", hit=False)
        obs_device.observe_execute("reduce", 0.1)
        rep = obs_device.report()
        assert rep["legacy_pin"] and not rep["enabled"]
        assert rep["families"] == {}


# --------------------------------------------- certified-byte identity
class TestByteIdentity:
    def test_certified_bytes_identical_armed_vs_disarmed(self, armed):
        """The AOT swap compiles the SAME program the jit cache would
        build: aggregate_flat bytes match exactly with the plane armed
        and under the BFLC_DEVICE_OBS=0 pin."""
        import hashlib

        from bflc_demo_tpu.meshagg.engine import ENGINE
        from bflc_demo_tpu.utils.serialization import pack_entries

        g, deltas, weights, selected = _scenario(7933)
        out_armed = ENGINE.aggregate_flat(g, deltas, weights, selected,
                                          0.3, force_leg="mesh")
        h_armed = hashlib.sha256(pack_entries(out_armed)).hexdigest()
        os.environ["BFLC_DEVICE_OBS"] = "0"
        out_legacy = ENGINE.aggregate_flat(g, deltas, weights, selected,
                                           0.3, force_leg="mesh")
        h_legacy = hashlib.sha256(pack_entries(out_legacy)).hexdigest()
        assert h_armed == h_legacy


# ------------------------------------------------------ storm detector
class TestStormDetector:
    def test_steady_state_has_zero_false_positives(self):
        det = obs_device.RecompileStormDetector(role="driver")
        verdicts = [det.observe_round(0, {"reduce": 3.0})["verdict"]]
        verdicts += [det.observe_round(r, {"reduce": 0.0})["verdict"]
                     for r in range(1, 30)]
        assert verdicts == ["ok"] * 30

    def test_warn_then_crit_escalation_then_recovery(self):
        det = obs_device.RecompileStormDetector(role="driver")
        det.observe_round(0, {"reduce": 3.0})       # cold start
        for r in range(1, 9):
            det.observe_round(r, {"reduce": 0.0})
        warn = det.observe_round(9, {"reduce": 1.0})
        assert warn["verdict"] == "warn"
        assert warn["families"]["reduce"]["z"] == pytest.approx(4.0)
        crit = det.observe_round(10, {"reduce": 1.0})
        assert crit["verdict"] == "crit"            # 2-round streak
        calm = det.observe_round(11, {"reduce": 0.0})
        assert calm["verdict"] == "ok"              # streak cleared

    def test_cold_start_and_min_baseline_never_judge(self):
        """Every family legitimately compiles on first appearance —
        warmup + min_baseline keep those rounds verdict-free."""
        det = obs_device.RecompileStormDetector(role="driver")
        for r in range(4):
            rec = det.observe_round(r, {"score": 5.0})
            assert rec["verdict"] == "ok"
            assert rec["families"]["score"]["z"] is None

    def test_crit_flushes_flight_and_triggers_xprof(self, armed):
        obs_device.XPROF = obs_device.XprofWindow(
            "", str(armed / "xp"))
        det = obs_device.RecompileStormDetector(role="driver")
        det.observe_round(0, {"reduce": 0.0})
        for r in range(1, 9):
            det.observe_round(r, {"reduce": 0.0})
        det.observe_round(9, {"reduce": 2.0})
        det.observe_round(10, {"reduce": 2.0})      # CRIT
        assert obs_device.XPROF._pending_trigger == "storm_crit"
        recs = obs_device.load_device_records(str(armed))
        crits = [r for r in recs if r["type"] == "device_storm"
                 and r["verdict"] == "crit"]
        assert crits and crits[-1]["epoch"] == 10
        assert crits[-1]["families"]["reduce"]["level"] == "crit"


# ----------------------------------------------------- memory plane
class TestMemoryWatermark:
    def test_cpu_fallback_chain_reports_a_real_watermark(self, armed):
        sample = obs_device.memory_sample()
        assert sample["source"] in ("rss", "getrusage", "tracemalloc",
                                    "device:cpu")
        assert sample["peak_bytes"] > 0

    def test_env_ceiling_fills_bytes_limit(self, armed, monkeypatch):
        monkeypatch.setenv("BFLC_DEVICE_MEM_CEILING_BYTES", "123456789")
        assert obs_device.memory_sample()["bytes_limit"] == 123456789.0

    def test_scrape_reason_appends_sink_record(self, armed):
        obs_device.sample_memory(reason="scrape")
        recs = [r for r in obs_device.load_device_records(str(armed))
                if r["type"] == "device_mem"]
        assert recs and recs[-1]["reason"] == "scrape"
        # unchanged peak on a plain tick: no new line per tick
        n = len(recs)
        obs_device.sample_memory(reason="tick")
        recs2 = [r for r in obs_device.load_device_records(str(armed))
                 if r["type"] == "device_mem"]
        assert len(recs2) == n


# --------------------------------------------------- xprof gating
class TestXprofGating:
    def test_unarmed_window_is_inert(self, armed):
        w = obs_device.XprofWindow("", "")
        assert not w.armed
        for r in range(5):
            w.on_round(r)
        w.trigger_once("storm_crit")     # no out_dir -> still inert
        assert not w.armed
        w.close()
        assert not [r for r in
                    obs_device.load_device_records(str(armed))
                    if r["type"] == "device_xprof"]

    def test_spec_parse_and_bad_spec(self, tmp_path):
        w = obs_device.XprofWindow("5:3", str(tmp_path))
        assert w.armed and w.start_round == 5 and w.count == 3
        bad = obs_device.XprofWindow("abc", str(tmp_path))
        assert bad.start_round is None and not bad.armed

    def test_arm_xprof_env_twin(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BFLC_XPROF", "7:2")
        monkeypatch.setenv("BFLC_XPROF_DIR", str(tmp_path))
        w = obs_device.arm_xprof()
        try:
            assert w.start_round == 7 and w.count == 2
            assert w.out_dir == str(tmp_path)
            assert obs_device.XPROF is w
        finally:
            obs_device.XPROF = None


# ------------------------------------------------- sink + loader
class TestSinkRoundtrip:
    def test_install_registers_terminal_flush_and_roundtrips(
            self, armed):
        obs_device.install(str(armed))
        assert obs_device._terminal_flush in obs_flight.TERMINAL_FLUSHES
        obs_device.record_compile("reduce", 0.02, flops=10.0)
        path = armed / "writer.device.jsonl"
        assert path.exists()
        with open(path, "a") as fh:
            fh.write('{"type": "device_compile", "family": ')  # torn
        other = armed / "client-1.device.jsonl"
        with open(other, "w") as fh:
            fh.write(json.dumps({"type": "device_mem", "t": 1.0,
                                 "peak_bytes": 5.0}) + "\n")
        recs = obs_device.load_device_records(str(armed))
        assert [r["type"] for r in recs] == ["device_mem",
                                             "device_compile"]
        assert recs[0]["role"] == "client-1"     # from the filename
        assert recs[1]["role"] == "writer"


# --------------------------------------------------- timeline join
def _snap(cum_compiles, peak=0.0, limit=0.0):
    m = {"device_compile_total": {"type": "counter", "samples": [
        {"labels": {"family": "reduce"}, "value": cum_compiles}]}}
    if peak:
        m["device_mem_peak_bytes"] = {"type": "gauge", "samples": [
            {"labels": {"source": "rss"}, "value": peak}]}
        m["device_mem_limit_bytes"] = {"type": "gauge", "samples": [
            {"labels": {"source": "rss"}, "value": limit}]}
    return {"metrics": m}


class TestTimelineJoin:
    def test_scrape_differencing_with_warmup_none(self):
        """Cumulative counters difference scrape-to-scrape; the first
        observation and the SLO warmup rounds report None, so warmup
        compiles can never breach the zero-tolerance objective."""
        tl = RoundTimeline()
        for r, cum in enumerate([3.0, 3.0, 4.0, 4.0]):
            tl.observe({"type": "note", "t": 100.0 + r,
                        "name": "round_commit", "epoch": r})
            tl.observe({"type": "scrape", "t": 100.1 + r,
                        "epoch": r + 1,
                        "roles": {"writer": _snap(
                            cum, peak=900.0, limit=1000.0)},
                        "coverage": {"answered": 1, "expected": 1,
                                     "missing": []}})
        assert tl.scrapes[0][0]["device_recompiles_delta"] is None
        assert tl.scrapes[1][0]["device_recompiles_delta"] == 0.0
        assert tl.scrapes[2][0]["device_recompiles_delta"] == 1.0
        assert DEVICE_SLO_WARMUP_ROUNDS == 2
        assert tl.slo_summary(1)["device_recompiles_delta"] is None
        assert tl.slo_summary(2)["device_recompiles_delta"] == 1.0
        assert tl.slo_summary(3)["device_recompiles_delta"] == 0.0
        assert tl.slo_summary(2)["device_mem_frac"] == \
            pytest.approx(0.9)
        rec = tl.round_record(2)
        assert rec["device"]["recompiles_delta"] == 1.0
        assert rec["device"]["mem_frac"] == pytest.approx(0.9)

    def test_device_records_join_round_record(self):
        tl = RoundTimeline()
        tl.observe({"type": "note", "t": 100.0, "name": "round_commit",
                    "epoch": 2})
        tl.observe_device({"type": "device_storm", "t": 100.2,
                           "role": "driver", "epoch": 2,
                           "verdict": "warn",
                           "families": {"reduce": {
                               "fresh": 1.0, "z": 4.0,
                               "level": "warn"}}})
        rec = tl.round_record(2)
        assert rec["device"]["storm"]["verdict"] == "warn"


# ----------------------------------------------- operator/CI gates
class TestOperatorGates:
    def test_chaos_soak_recompile_storm_gate(self, tmp_path):
        soak = _tool("chaos_soak")
        stormy = tmp_path / "stormy"
        stormy.mkdir()
        with open(stormy / "driver.device.jsonl", "w") as fh:
            fh.write(json.dumps({
                "type": "device_storm", "t": 1.0, "epoch": 9,
                "verdict": "crit", "families": {
                    "reduce": {"fresh": 2.0, "z": 8.0,
                               "level": "crit"}}}) + "\n")
            fh.write(json.dumps({
                "type": "device_storm", "t": 2.0, "epoch": 10,
                "verdict": "ok", "families": {}}) + "\n")
        g = soak.operator_gates(str(stormy), fail_on_storm=True)
        assert len(g["storm_rounds"]) == 1
        assert g["storm_rounds"][0]["epoch"] == 9
        assert g["storm_rounds"][0]["families"] == ["reduce"]
        assert any("recompile-storm" in f for f in g["failures"])
        # unarmed: recorded as evidence, never a failure
        g2 = soak.operator_gates(str(stormy))
        assert g2["storm_rounds"] and not g2["failures"]

    def test_steady_state_recompile_gate_holds(self, armed):
        """check_reduction_spec's repeated-scenario gate: the second
        and later passes of one fixed scenario add ZERO fresh XLA
        programs (the in-process twin of the fleet evidence)."""
        from check_reduction_spec import run_steady_state_check
        out = run_steady_state_check(repeats=2, max_n=8)
        assert out["fresh_after_warmup"] == 0
        assert len(out["compile_totals"]) == 2


# ----------------------------------------------- bench artifact schema
class TestBenchSchema:
    def test_report_is_the_bench_device_section(self, armed):
        obs_device.record_compile("reduce", 0.01, flops=100.0,
                                  bytes_accessed=400.0)
        obs_device.record_cache("reduce", hit=True)
        obs_device.observe_execute("reduce", 0.001)
        rep = obs_device.report()
        assert set(rep) == {"enabled", "legacy_pin", "platform",
                            "families", "memory",
                            "cost_analysis_unavailable",
                            "aot_fallbacks"}
        fam = rep["families"]["reduce"]
        assert set(fam) == {"compiles", "compile_seconds", "flops",
                            "bytes", "cache_hits", "cache_misses",
                            "execute_calls"}
        assert fam["compiles"] == 1 and fam["flops"] == 100.0
        assert set(rep["memory"]) >= {"source", "bytes_in_use",
                                      "peak_bytes"}
        assert json.loads(json.dumps(rep))      # artifact-serializable
