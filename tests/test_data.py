"""Data pipeline tests (reference semantics: main.py:33-53)."""

import numpy as np

from bflc_demo_tpu.data import (load_occupancy, synthesize_occupancy,
                                iid_shards, dirichlet_shards, one_hot)


def test_occupancy_shapes_and_split():
    xtr, ytr, xte, yte = load_occupancy()
    n = len(xtr) + len(xte)
    assert xtr.shape[1] == 5
    assert set(np.unique(ytr)) <= {0, 1}
    # 75/25 split like train_test_split(test_size=.25) (main.py:41-42)
    assert abs(len(xte) / n - 0.25) < 0.01


def test_synthetic_matches_schema():
    x, y = synthesize_occupancy(n=1000, seed=3)
    assert x.shape == (1000, 5) and y.shape == (1000,)
    assert 0.1 < y.mean() < 0.35  # imbalance like 1729/8143


def test_iid_shards_cover_all():
    x, y = synthesize_occupancy(n=1001, seed=0)
    shards = iid_shards(x, y, 20)
    assert len(shards) == 20
    assert sum(len(sx) for sx, _ in shards) == 1001
    # np.array_split near-equality (main.py:47-48)
    sizes = [len(sx) for sx, _ in shards]
    assert max(sizes) - min(sizes) <= 1


def test_dirichlet_skew_and_coverage():
    x, y = synthesize_occupancy(n=4000, seed=1)
    shards = dirichlet_shards(x, y, 10, alpha=0.3, seed=1)
    assert sum(len(sx) for sx, _ in shards) == 4000
    assert all(len(sx) >= 2 for sx, _ in shards)
    # skew: per-client positive rates should vary much more than iid
    rates = np.array([sy.mean() for _, sy in shards])
    assert rates.std() > 0.05


def test_explicit_missing_path_raises():
    import pytest
    with pytest.raises(FileNotFoundError):
        load_occupancy(path="/nonexistent/datatraining.txt")


def test_dirichlet_impossible_split_raises():
    import pytest
    x, y = synthesize_occupancy(n=30, seed=2)
    with pytest.raises(ValueError):
        dirichlet_shards(x, y, num_clients=25, alpha=0.05, seed=0, min_size=5)


def test_one_hot():
    oh = one_hot(np.array([0, 1, 1]), 2)
    np.testing.assert_array_equal(oh, [[1, 0], [0, 1], [0, 1]])


def test_real_npz_preferred_over_synthetic(tmp_path, monkeypatch):
    """BFLC_DATA_DIR/<name>.npz wins over the synthetic generator, with
    geometry validation so a mislabeled file fails loudly."""
    import pytest
    from bflc_demo_tpu.data.synthetic import synthetic_cifar10
    x = np.random.default_rng(0).random((50, 32, 32, 3)).astype(np.float32)
    y = np.arange(50, dtype=np.int32) % 10
    np.savez(tmp_path / "cifar10.npz", x=x, y=y)
    monkeypatch.setenv("BFLC_DATA_DIR", str(tmp_path))
    gx, gy = synthetic_cifar10(n=30, seed=1)
    assert gx.shape == (30, 32, 32, 3)          # subsampled real file
    assert set(np.unique(gy)) <= set(range(10))
    # the same rows came from the file, not the generator
    flat_file = {xx.tobytes() for xx in x}
    assert all(xx.tobytes() in flat_file for xx in gx)
    # every mismatch fails loudly, never silently trains wrong
    from bflc_demo_tpu.data.synthetic import _real_or_synthetic
    np.savez(tmp_path / "cifar100.npz", x=x, y=y)
    with pytest.raises(ValueError, match="images"):        # wrong geometry
        _real_or_synthetic("cifar100", 30, (28, 28, 1), 100, 0)
    np.savez(tmp_path / "mnist.npz",
             x=(x[:, :, :, :1] * 255).reshape(50, 32, 32), y=y)
    with pytest.raises(ValueError, match="images"):
        _real_or_synthetic("mnist", 30, (28, 28, 1), 10, 0)
    np.savez(tmp_path / "femnist.npz", x=x[:, :, :, :1][:, 2:30, 2:30] * 255,
             y=y)
    with pytest.raises(ValueError, match=r"\[0, 1\]"):     # 0-255 scale
        _real_or_synthetic("femnist", 30, (28, 28, 1), 62, 0)
    yneg = y.copy(); yneg[0] = -1
    np.savez(tmp_path / "cifar10.npz", x=x, y=yneg)
    with pytest.raises(ValueError, match="labels span"):   # negative label
        _real_or_synthetic("cifar10", 30, (32, 32, 3), 10, 0)
    np.savez(tmp_path / "cifar10.npz", x=x, y=y)
    with pytest.raises(ValueError, match="samples <"):     # too few rows
        _real_or_synthetic("cifar10", 500, (32, 32, 3), 10, 0)
