"""Write-ahead log tests: durability, crash recovery (torn record), native
vs python byte-identical WAL files, cross-backend replay."""


import pytest

from bflc_demo_tpu.ledger import make_ledger, LedgerStatus, bindings
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3)

BACKENDS = ["python"] + (["native"] if bindings.native_available() else [])


def addr(i):
    return f"0x{i:03x}"


def _run_traffic(led, epochs=2):
    for i in range(CFG.client_num):
        led.register_node(addr(i))
    for ep in range(epochs):
        senders = [i for i in range(CFG.client_num)
                   if led.query_state(addr(i))[0] == "trainer"][:3]
        for i in senders:
            led.upload_local_update(addr(i), bytes([i, ep]) * 16, 100 + i,
                                    1.0, ep)
        for c in led.committee():
            led.upload_scores(c, ep, [0.5, 0.7, 0.6])
        led.commit_model(bytes([ep]) * 32, ep)


@pytest.mark.parametrize("backend", BACKENDS)
def test_wal_written_and_replayed(tmp_path, backend):
    path = str(tmp_path / "ledger.wal")
    led = make_ledger(CFG, backend=backend)
    assert led.attach_wal(path)
    _run_traffic(led)
    led.detach_wal()

    fresh = make_ledger(CFG, backend=backend)
    applied = fresh.replay_wal(path)
    assert applied == led.log_size()
    assert fresh.log_head() == led.log_head()
    assert fresh.epoch == led.epoch
    assert fresh.committee() == led.committee()


@pytest.mark.parametrize("backend", BACKENDS)
def test_attach_mid_stream_includes_history(tmp_path, backend):
    """Attaching after some traffic writes the whole accepted history."""
    path = str(tmp_path / "late.wal")
    led = make_ledger(CFG, backend=backend)
    for i in range(CFG.client_num):
        led.register_node(addr(i))
    assert led.attach_wal(path)
    led.upload_local_update(addr(2), b"\1" * 32, 100, 1.0, 0)
    led.detach_wal()
    fresh = make_ledger(CFG, backend=backend)
    assert fresh.replay_wal(path) == CFG.client_num + 1
    assert fresh.log_head() == led.log_head()


@pytest.mark.parametrize("backend", BACKENDS)
def test_torn_trailing_record_skipped(tmp_path, backend):
    """A crash mid-append leaves a torn record; recovery applies everything
    before it and stops cleanly."""
    path = str(tmp_path / "torn.wal")
    led = make_ledger(CFG, backend=backend)
    led.attach_wal(path)
    _run_traffic(led, epochs=1)
    led.detach_wal()
    full = led.log_size()
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-5])       # tear the last record
    fresh = make_ledger(CFG, backend=backend)
    applied = fresh.replay_wal(path)
    assert applied == full - 1
    assert fresh.verify_log()


def test_native_and_python_wal_files_identical(tmp_path):
    if not bindings.native_available():
        pytest.skip("native ledger unavailable")
    p_nat = str(tmp_path / "nat.wal")
    p_py = str(tmp_path / "py.wal")
    nat = make_ledger(CFG, backend="native")
    py = make_ledger(CFG, backend="python")
    nat.attach_wal(p_nat)
    py.attach_wal(p_py)
    _run_traffic(nat)
    _run_traffic(py)
    nat.detach_wal()
    py.detach_wal()
    assert open(p_nat, "rb").read() == open(p_py, "rb").read()
    # cross-backend recovery: python replica from the native WAL
    replica = make_ledger(CFG, backend="python")
    assert replica.replay_wal(p_nat) == nat.log_size()
    assert replica.log_head() == nat.log_head()


def _compacted_wal(tmp_path):
    """A WAL2 journal: traffic, certified-snapshot GC, one tail round."""
    from bflc_demo_tpu.ledger.snapshot import make_snapshot_op
    path = str(tmp_path / "compacted.wal")
    led = make_ledger(CFG, backend="python")
    led.attach_wal(path)
    _run_traffic(led, epochs=2)
    assert led.apply_op(make_snapshot_op(led)) == LedgerStatus.OK
    led.gc_prefix(led.log_size(), None)     # rewrites the journal (WAL2)
    senders = [i for i in range(CFG.client_num)
               if led.query_state(addr(i))[0] == "trainer"][:3]
    for i in senders:
        led.upload_local_update(addr(i), bytes([i, 2]) * 16, 100, 1.0, 2)
    led.detach_wal()
    return path, led


def test_compacted_wal_torn_tail_record_skipped(tmp_path):
    """Crash-tear interaction with compaction (ledger.snapshot): a torn
    TAIL record in a compacted (WAL2) journal recovers exactly like the
    WAL1 case — snapshot header installs, intact tail applies, the torn
    record is skipped."""
    path, led = _compacted_wal(tmp_path)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-5])       # tear the last record
    fresh = make_ledger(CFG, backend="python")
    applied = fresh.replay_wal(path)
    assert fresh.log_base == led.log_base   # the snapshot base installed
    assert fresh.log_size() == led.log_size() - 1
    assert applied == led.log_size() - led.log_base - 1
    assert fresh.verify_log()


def test_compacted_wal_torn_header_refuses_whole_file(tmp_path):
    """A tear INSIDE the WAL2 snapshot header must refuse the whole
    journal: the snapshot state is the tail's ground truth, so there is
    nothing safe to salvage without it (operators fall back to the
    retained artifact + tools/ledger_gc.py)."""
    path, _ = _compacted_wal(tmp_path)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:20])       # mid-header truncation
    fresh = make_ledger(CFG, backend="python")
    with pytest.raises(ValueError):
        fresh.replay_wal(path)
    # a bit-flip in the snapshot state bytes refuses too (the canonical
    # decode is length-exact; a half-installed base must never happen)
    path2, _ = _compacted_wal(tmp_path)
    blob = bytearray(open(path2, "rb").read())
    blob[60] ^= 0x04                        # inside the state bytes
    open(path2, "wb").write(bytes(blob))
    fresh2 = make_ledger(CFG, backend="python")
    with pytest.raises(ValueError):
        fresh2.replay_wal(path2)
    # the offline tool surface refuses the same tear cleanly: wal_base
    # on a header torn inside the base field raises ValueError, never a
    # raw struct.error (tools/ledger_gc.py inspect reports it)
    from bflc_demo_tpu.ledger.tool import wal_base
    assert wal_base(path2) >= 0            # intact header still reads
    head = open(path, "rb").read()[:12]    # magic + 4 of 8 base bytes
    open(path, "wb").write(head)
    with pytest.raises(ValueError):
        wal_base(path)


def test_compacted_wal_refuses_nonfresh_ledger(tmp_path):
    """WAL2 replays only into a fresh ledger — installing a snapshot
    base over live state would silently fork the replica."""
    path, _ = _compacted_wal(tmp_path)
    used = make_ledger(CFG, backend="python")
    used.register_node(addr(0))
    with pytest.raises(ValueError):
        used.replay_wal(path)


@pytest.mark.parametrize("backend", BACKENDS)
def test_bad_wal_rejected(tmp_path, backend):
    path = str(tmp_path / "junk.wal")
    open(path, "wb").write(b"definitely not a wal")
    fresh = make_ledger(CFG, backend=backend)
    with pytest.raises(ValueError):
        fresh.replay_wal(path)
    # missing file: same exception type on both backends (parity contract)
    with pytest.raises(ValueError):
        fresh.replay_wal(str(tmp_path / "nope.wal"))
