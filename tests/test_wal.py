"""Write-ahead log tests: durability, crash recovery (torn record), native
vs python byte-identical WAL files, cross-backend replay."""


import pytest

from bflc_demo_tpu.ledger import make_ledger, LedgerStatus, bindings
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3)

BACKENDS = ["python"] + (["native"] if bindings.native_available() else [])


def addr(i):
    return f"0x{i:03x}"


def _run_traffic(led, epochs=2):
    for i in range(CFG.client_num):
        led.register_node(addr(i))
    for ep in range(epochs):
        senders = [i for i in range(CFG.client_num)
                   if led.query_state(addr(i))[0] == "trainer"][:3]
        for i in senders:
            led.upload_local_update(addr(i), bytes([i, ep]) * 16, 100 + i,
                                    1.0, ep)
        for c in led.committee():
            led.upload_scores(c, ep, [0.5, 0.7, 0.6])
        led.commit_model(bytes([ep]) * 32, ep)


@pytest.mark.parametrize("backend", BACKENDS)
def test_wal_written_and_replayed(tmp_path, backend):
    path = str(tmp_path / "ledger.wal")
    led = make_ledger(CFG, backend=backend)
    assert led.attach_wal(path)
    _run_traffic(led)
    led.detach_wal()

    fresh = make_ledger(CFG, backend=backend)
    applied = fresh.replay_wal(path)
    assert applied == led.log_size()
    assert fresh.log_head() == led.log_head()
    assert fresh.epoch == led.epoch
    assert fresh.committee() == led.committee()


@pytest.mark.parametrize("backend", BACKENDS)
def test_attach_mid_stream_includes_history(tmp_path, backend):
    """Attaching after some traffic writes the whole accepted history."""
    path = str(tmp_path / "late.wal")
    led = make_ledger(CFG, backend=backend)
    for i in range(CFG.client_num):
        led.register_node(addr(i))
    assert led.attach_wal(path)
    led.upload_local_update(addr(2), b"\1" * 32, 100, 1.0, 0)
    led.detach_wal()
    fresh = make_ledger(CFG, backend=backend)
    assert fresh.replay_wal(path) == CFG.client_num + 1
    assert fresh.log_head() == led.log_head()


@pytest.mark.parametrize("backend", BACKENDS)
def test_torn_trailing_record_skipped(tmp_path, backend):
    """A crash mid-append leaves a torn record; recovery applies everything
    before it and stops cleanly."""
    path = str(tmp_path / "torn.wal")
    led = make_ledger(CFG, backend=backend)
    led.attach_wal(path)
    _run_traffic(led, epochs=1)
    led.detach_wal()
    full = led.log_size()
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-5])       # tear the last record
    fresh = make_ledger(CFG, backend=backend)
    applied = fresh.replay_wal(path)
    assert applied == full - 1
    assert fresh.verify_log()


def test_native_and_python_wal_files_identical(tmp_path):
    if not bindings.native_available():
        pytest.skip("native ledger unavailable")
    p_nat = str(tmp_path / "nat.wal")
    p_py = str(tmp_path / "py.wal")
    nat = make_ledger(CFG, backend="native")
    py = make_ledger(CFG, backend="python")
    nat.attach_wal(p_nat)
    py.attach_wal(p_py)
    _run_traffic(nat)
    _run_traffic(py)
    nat.detach_wal()
    py.detach_wal()
    assert open(p_nat, "rb").read() == open(p_py, "rb").read()
    # cross-backend recovery: python replica from the native WAL
    replica = make_ledger(CFG, backend="python")
    assert replica.replay_wal(p_nat) == nat.log_size()
    assert replica.log_head() == nat.log_head()


@pytest.mark.parametrize("backend", BACKENDS)
def test_bad_wal_rejected(tmp_path, backend):
    path = str(tmp_path / "junk.wal")
    open(path, "wb").write(b"definitely not a wal")
    fresh = make_ledger(CFG, backend=backend)
    with pytest.raises(ValueError):
        fresh.replay_wal(path)
    # missing file: same exception type on both backends (parity contract)
    with pytest.raises(ValueError):
        fresh.replay_wal(str(tmp_path / "nope.wal"))
