"""Fleet telemetry plane (bflc_demo_tpu.obs): metrics registry semantics,
thread-local tracer spans, flight-recorder durability past SIGKILL, the
telemetry scrape RPC + FleetCollector, and collector degradation under
wire faults (the observability PR's contract: the plane keeps observing
exactly when the fleet is failing).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs.collector import (FleetCollector, load_timeline,
                                         publish_snapshot,
                                         read_snapshot_file)
from bflc_demo_tpu.obs.flight import FlightRecorder, load_flight
from bflc_demo_tpu.obs.metrics import MetricsRegistry, to_prometheus
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils import tracing


class TestMetricsRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry(enabled=True, role="t")
        c = reg.counter("reqs_total", "requests", ("method",))
        c.inc(method="upload")
        c.inc(2.5, method="upload")
        c.inc(method="info")
        g = reg.gauge("round")
        g.set(7)
        g.inc(); g.dec(2)
        h = reg.histogram("lat", "latency", buckets=(0.01, 0.1, 1.0))
        h.observe(0.05)
        h.observe(50.0)
        snap = reg.snapshot()
        json.dumps(snap)                    # JSON-able end to end
        m = snap["metrics"]
        by_label = {s["labels"]["method"]: s["value"]
                    for s in m["reqs_total"]["samples"]}
        assert by_label == {"upload": 3.5, "info": 1.0}
        assert m["round"]["samples"][0]["value"] == 6.0
        hs = m["lat"]["samples"][0]
        assert hs["count"] == 2 and hs["sum"] == pytest.approx(50.05)
        # buckets are CUMULATIVE (Prometheus convention): +Inf == count
        assert hs["buckets"]["+Inf"] == 2
        assert hs["buckets"]["0.1"] == 1

    def test_timer_context_manager(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("dur", "", ("k",))
        with h.time(k="a"):
            time.sleep(0.01)
        s = reg.snapshot()["metrics"]["dur"]["samples"][0]
        assert s["count"] == 1 and s["sum"] >= 0.008

    def test_bounded_cardinality_folds_to_overflow(self):
        reg = MetricsRegistry(enabled=True)
        c = reg.counter("x", "", ("k",))
        for i in range(300):
            c.inc(k=str(i))
        snap = reg.snapshot()
        samples = snap["metrics"]["x"]["samples"]
        assert len(samples) <= reg.max_series_per_metric + 1
        assert snap["series_dropped"] > 0
        overflow = [s for s in samples
                    if s["labels"].get("overflow") == "true"]
        assert overflow and overflow[0]["value"] > 0

    def test_disabled_registry_is_noop(self):
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("y")
        h = reg.histogram("z")
        c.inc()
        h.observe(1.0)
        with h.time():
            pass
        snap = reg.snapshot()
        assert snap["metrics"]["y"]["samples"] == []
        assert snap["metrics"]["z"]["samples"] == []

    def test_redeclaration_idempotent_but_conflicts_raise(self):
        reg = MetricsRegistry(enabled=True)
        a = reg.counter("n", "h", ("k",))
        assert reg.counter("n", "h", ("k",)) is a
        with pytest.raises(ValueError):
            reg.gauge("n")
        with pytest.raises(ValueError):
            reg.counter("n", "h", ("other",))

    def test_snapshot_absorbs_tracer_costs(self):
        reg = MetricsRegistry(enabled=True)
        saved = tracing.PROC.enabled
        tracing.PROC.enabled = True
        try:
            tracing.PROC.charge("test.category_s", 1.25)
            snap = reg.snapshot()
            assert snap["trace_costs"]["test.category_s"] == 1.25
        finally:
            tracing.PROC.enabled = saved
            with tracing.PROC._lock:
                tracing.PROC.costs.pop("test.category_s", None)

    def test_prometheus_text_format(self):
        reg = MetricsRegistry(enabled=True, role="writer")
        reg.counter("ops_total", "ops", ("kind",)).inc(3, kind="up")
        reg.histogram("lat", "", buckets=(0.1,)).observe(0.05)
        text = to_prometheus([reg.snapshot()])
        assert '# TYPE bflc_ops_total counter' in text
        assert 'bflc_ops_total{kind="up",role="writer"} 3.0' in text
        assert 'bflc_lat_bucket{le="0.1",role="writer"} 1' in text
        assert 'bflc_lat_count{role="writer"} 1' in text


class TestTracerThreadLocalSpans:
    """Satellite regression: `Tracer.span` used to share ONE name stack
    across threads (utils/tracing.py documented the hazard) — two
    threads nesting spans interleaved their path prefixes.  The stack is
    now thread-local: every span path must be built from its own
    thread's ancestry only."""

    def test_two_threads_produce_uncrossed_span_paths(self):
        tr = tracing.Tracer(enabled=True)
        start = threading.Barrier(2)

        def worker(name):
            start.wait()
            for _ in range(50):
                with tr.span(f"outer-{name}"):
                    with tr.span(f"inner-{name}"):
                        time.sleep(0)       # force interleaving

        ts = [threading.Thread(target=worker, args=(n,))
              for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        paths = {e["name"] for e in tr.events if e["type"] == "span"}
        assert paths == {"outer-a", "outer-a/inner-a",
                         "outer-b", "outer-b/inner-b"}, paths

    def test_nested_path_still_builds_within_one_thread(self):
        tr = tracing.Tracer(enabled=True)
        with tr.span("a"):
            with tr.span("b"):
                tr.event("e")
        names = [e["name"] for e in tr.events]
        assert "a/b/e" in names and "a/b" in names and "a" in names


class TestFlightRecorder:
    def test_sigkill_leaves_parseable_dump(self, tmp_path):
        """The chaos contract: a SIGKILLed role's flight file exists and
        parses (periodic flush + atomic rename — no torn files)."""
        code = textwrap.dedent(f"""
            import time
            from bflc_demo_tpu import obs
            from bflc_demo_tpu.obs import flight
            obs.install_process_telemetry(
                "victim", {str(tmp_path)!r}, interval_s=0.1)
            for i in range(10_000):
                flight.FLIGHT.record("event", "tick", i=i)
                time.sleep(0.01)
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen([sys.executable, "-c", code], env=env,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        path = tmp_path / "victim.flight.jsonl"
        deadline = time.monotonic() + 30.0
        # wait until the victim demonstrably recorded some ticks
        while time.monotonic() < deadline:
            try:
                if len(load_flight(str(path))["events"]) >= 3:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)
        dump = load_flight(str(path))
        assert dump["header"]["role"] == "victim"
        ticks = [e for e in dump["events"] if e["name"] == "tick"]
        assert len(ticks) >= 3
        # the metrics snapshot file was published too
        snap = read_snapshot_file(str(tmp_path / "victim.metrics.json"))
        assert snap is not None and snap["role"] == "victim"

    def test_ring_is_bounded_and_flush_atomic(self, tmp_path):
        rec = FlightRecorder(capacity=16)
        rec.enabled = True
        rec.path = str(tmp_path / "r.flight.jsonl")
        for i in range(100):
            rec.record("event", "e", i=i)
        assert rec.flush("test")
        dump = load_flight(rec.path)
        assert dump["header"]["reason"] == "test"
        assert len(dump["events"]) == 16
        assert dump["events"][-1]["i"] == 99      # newest survives

    def test_load_flight_rejects_headerless_garbage(self, tmp_path):
        p = tmp_path / "bad.flight.jsonl"
        p.write_text('{"no": "header"}\n')
        with pytest.raises(ValueError):
            load_flight(str(p))


def _mini_control_plane(n_clients=4, validators=4):
    """Writer + validator fleet, thread-served in this process, one
    complete protocol round driven through the socket (the
    profile_round topology, shrunk)."""
    import hashlib
    import struct

    from bflc_demo_tpu.comm.bft import ValidatorNode, provision_validators
    from bflc_demo_tpu.comm.identity import _op_bytes, provision_wallets
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    from bflc_demo_tpu.utils.serialization import pack_pytree

    cfg = ProtocolConfig(client_num=n_clients, comm_count=2,
                         aggregate_count=2, needed_update_count=2,
                         learning_rate=0.05, batch_size=16)
    wallets, _ = provision_wallets(n_clients, b"obs-test-seed-000001")
    vwallets, vkeys = provision_validators(validators,
                                           b"obs-test-validators-01")
    blob0 = pack_pytree({"W": np.zeros((5, 2), np.float32)})
    nodes = [ValidatorNode(cfg, w, i, validator_keys=vkeys)
             for i, w in enumerate(vwallets)]
    for v in nodes:
        v.start()
    server = LedgerServer(cfg, blob0,
                          bft_validators=[(v.host, v.port)
                                          for v in nodes],
                          bft_keys=vkeys)
    server.start()
    client = CoordinatorClient(server.host, server.port)

    def sign(w, kind, epoch, payload):
        return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()

    for w in wallets:
        r = client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=sign(w, "register", 0, b""))
        assert r["ok"], r
    committee = set(client.request("committee")["committee"])
    trainers = [w for w in wallets if w.address not in committee]
    for i, w in enumerate(trainers[:2]):
        blob = pack_pytree({"W": np.full((5, 2), 0.1 * (i + 1),
                                         np.float32)})
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", 10 + i, 1.0)
        r = client.request("upload", addr=w.address, blob=blob,
                           hash=digest.hex(), n=10 + i, cost=1.0,
                           epoch=0, tag=sign(w, "upload", 0, payload))
        assert r["ok"], r
    return cfg, server, nodes, client


@pytest.fixture
def enabled_registry():
    """Flip the process registry on for the test, restore after (it is
    process-global state)."""
    saved_enabled = obs_metrics.REGISTRY.enabled
    saved_role = obs_metrics.REGISTRY.role
    obs_metrics.REGISTRY.enabled = True
    try:
        yield obs_metrics.REGISTRY
    finally:
        obs_metrics.REGISTRY.enabled = saved_enabled
        obs_metrics.REGISTRY.role = saved_role


class TestTelemetryRPCAndCollector:
    def test_scrape_all_roles_jsonl_prom_and_fleet_top(
            self, tmp_path, enabled_registry):
        cfg, server, nodes, client = _mini_control_plane()
        try:
            jsonl = str(tmp_path / "metrics.jsonl")
            # a file-published role rides the same scrape (what clients
            # and standbys do in the process federation)
            fpath = str(tmp_path / "client-x.metrics.json")
            assert publish_snapshot(fpath)
            coll = FleetCollector(
                {"writer": (server.host, server.port),
                 **{f"validator-{i}": (v.host, v.port)
                    for i, v in enumerate(nodes)}},
                {"client-x": fpath}, jsonl_path=jsonl)
            coll.note("round_commit", epoch=0)
            rec = coll.scrape(tag="round-0")
            assert rec["coverage"]["answered"] == 6
            assert rec["coverage"]["missing"] == []
            wsnap = rec["roles"]["writer"]
            # writer gauges sampled at scrape time
            names = set(wsnap["metrics"])
            assert {"round", "uncertified_backlog",
                    "rpc_latency_seconds"} <= names
            # validators answered with their own metrics + role
            vsnap = rec["roles"]["validator-0"]
            assert "vote_latency_seconds" in vsnap["metrics"]
            # tracer costs absorbed into the snapshot
            assert isinstance(wsnap["trace_costs"], dict)

            # artifacts: jsonl timeline + Prometheus dump
            prom = str(tmp_path / "metrics.prom")
            assert coll.write_prometheus(prom)
            text = open(prom).read()
            assert "bflc_rpc_latency_seconds" in text
            assert 'role="writer"' in text
            tl = load_timeline(jsonl)
            assert [r["type"] for r in tl] == ["note", "scrape"]

            # fleet_top renders both views without raising
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))), "tools"))
            try:
                import fleet_top
            finally:
                sys.path.pop(0)
            once = fleet_top.render_once(tl)
            assert "writer" in once and "validator-0" in once
            timeline = fleet_top.render_timeline(tl)
            assert "round_commit" in timeline
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()

    def test_wire_frame_mix_counted(self, tmp_path, enabled_registry):
        cfg, server, nodes, client = _mini_control_plane()
        try:
            coll = FleetCollector({"writer": (server.host, server.port)})
            rec = coll.scrape()
            frames = rec["roles"]["writer"]["metrics"][
                "wire_frames_total"]["samples"]
            kinds = {(s["labels"]["dir"], s["labels"]["kind"]):
                     s["value"] for s in frames}
            # uploads carried binary blob frames; control replies are json
            assert kinds.get(("in", "bin"), 0) >= 1
            assert kinds.get(("out", "json"), 0) >= 1
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()


class TestTraceZeroOverheadWhenOff:
    """Sampling disabled (the default) must mean literally nothing on
    the hot path: no span records, no context, no `_tp` wire bytes, and
    the null span a shared singleton (no per-call allocation)."""

    def test_disabled_recorder_allocates_and_sends_nothing(self):
        from bflc_demo_tpu.comm import wire
        from bflc_demo_tpu.obs import trace as obs_trace
        t = obs_trace.TRACE
        assert not t.enabled            # default in the test process
        before = len(t._ring)
        with t.start_trace("root", epoch=1) as sp:
            sp["attr"] = "ignored"
            with t.span("child"):
                assert t.current_traceparent() is None
        assert len(t._ring) == before
        # the null span is ONE object, returned by every entry point
        assert t.span("a") is t.start_trace("b") \
            is t.span_from(None, "c") \
            is obs_trace.server_span({"_tp": "x"}, "d")
        # and the wire encoding is byte-identical to an untraced sender
        with t.start_trace("root"):
            assert wire._encode({"method": "m"}) == b'{"method":"m"}'

    def test_upload_lag_histogram_writer_side(self, enabled_registry):
        """Straggler-evidence satellite: every admitted upload observes
        its lag behind the round's first admitted upload into
        `upload_lag_seconds` (the async-aggregation baseline metric),
        exported via the existing scrape."""
        def lag_sample():
            snap = obs_metrics.REGISTRY.snapshot()
            m = snap["metrics"].get("upload_lag_seconds")
            return (m or {}).get("samples") or [{"count": 0, "sum": 0.0}]

        before = lag_sample()[0]["count"]
        cfg, server, nodes, client = _mini_control_plane()
        try:
            s = lag_sample()[0]
            # the mini plane admitted two uploads in epoch 0: the first
            # observes lag 0, the second a tiny positive lag
            assert s["count"] == before + 2
            assert s["sum"] < 5.0       # both lags are sub-second
            rec = FleetCollector(
                {"writer": (server.host, server.port)}).scrape()
            assert "upload_lag_seconds" in \
                rec["roles"]["writer"]["metrics"]
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()


class TestHistogramQuantiles:
    """Satellite: p50/p95/p99 from the exported cumulative buckets —
    the ONE quantile rule (obs.metrics.hist_quantile) fleet_top's
    straggler/staleness panels render instead of means."""

    def test_quantiles_from_exported_sample(self):
        from bflc_demo_tpu.obs.metrics import hist_quantile
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("lat", "", buckets=(0.01, 0.1, 1.0, 10.0))
        for _ in range(90):
            h.observe(0.05)
        for _ in range(9):
            h.observe(0.5)
        h.observe(5.0)
        s = reg.snapshot()["metrics"]["lat"]["samples"][0]
        # upper-bucket-bound estimates: conservative, never under-read
        assert hist_quantile(s, 0.5) == 0.1
        assert hist_quantile(s, 0.95) == 1.0
        assert hist_quantile(s, 0.999) == 10.0
        assert hist_quantile({"count": 0}, 0.5) == 0.0

    def test_overflow_bucket_reads_inf(self):
        from bflc_demo_tpu.obs.metrics import hist_quantile
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("x", "", buckets=(1.0,))
        h.observe(100.0)
        s = reg.snapshot()["metrics"]["x"]["samples"][0]
        assert hist_quantile(s, 0.5) == float("inf")

    def test_merge_across_label_sets(self):
        from bflc_demo_tpu.obs.metrics import (hist_quantile,
                                               merge_hist_samples)
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("m", "", ("k",), buckets=(1.0, 2.0))
        for _ in range(3):
            h.observe(0.5, k="a")
        h.observe(1.5, k="b")
        merged = merge_hist_samples(
            reg.snapshot()["metrics"]["m"]["samples"])
        assert merged["count"] == 4
        assert hist_quantile(merged, 0.5) == 1.0
        assert hist_quantile(merged, 0.99) == 2.0

    def test_fleet_top_renders_tails_not_means(self):
        """The straggler panel (upload_lag_seconds) and the async
        staleness panel surface p50/p95/p99 (rendered off a LOCAL
        registry snapshot — _role_row takes any snapshot dict)."""
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        try:
            import fleet_top
        finally:
            sys.path.pop(0)
        reg = MetricsRegistry(enabled=True, role="writer")
        lag = reg.histogram("upload_lag_seconds", "")
        for v in (0.01, 0.02, 0.03, 2.0):
            lag.observe(v)
        st = reg.histogram(
            "async_admitted_staleness", "",
            buckets=(0, 1, 2, 3, 5, 8, 13, 21, float("inf")))
        for v in (0, 0, 1, 8):
            st.observe(v)
        reg.counter("async_aggregations_total", "").inc()
        row = fleet_top._role_row("writer", reg.snapshot())
        assert "lag p50/95/99" in row
        assert "staleness p50/95/99" in row

    def test_fleet_top_renders_cell_tier_health(self):
        """Review regression: member-level health verdicts live at the
        CELL aggregator — its fleet_top row (and the timeline digest)
        must render them, or a cell-tier CRIT is invisible live."""
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        try:
            import fleet_top
        finally:
            sys.path.pop(0)
        reg = MetricsRegistry(enabled=True, role="cell-1")
        reg.gauge("cell_admitted", "").set(3)
        reg.gauge("health_verdict", "").set(2)
        reg.gauge("health_flagged_senders", "").set(1)
        reg.counter("health_verdicts_total", "", ("level",)).inc(
            level="crit")
        snap = reg.snapshot()
        row = fleet_top._role_row("cell-1", snap)
        assert "health CRIT" in row and "flagged 1" in row
        digest = fleet_top._scrape_digest(
            {"roles": {"cell-1": snap}})
        assert "cell-1: health=CRIT" in digest


def _async_cfg():
    import dataclasses
    return dataclasses.replace(
        ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                       needed_update_count=3, learning_rate=0.05,
                       batch_size=16, async_buffer=3,
                       max_staleness=4)).validate()


def _async_aupload(server, addr, i, base_epoch):
    import hashlib as _hl

    from bflc_demo_tpu.utils.serialization import pack_pytree
    blob = pack_pytree({"W": np.full((5, 2), 0.1 * (i + 1),
                                     np.float32)})
    d = _hl.sha256(blob).digest()
    return server._dispatch("aupload", {
        "addr": addr, "blob": blob, "hash": d.hex(), "n": 10 + i,
        "cost": 1.0, "base_epoch": base_epoch})


class TestAsyncTelemetryScrape:
    """Satellite: async-mode scrape coverage — the fault-degradation
    tests only covered sync roles; `async_buffer_depth` and
    `async_admitted_staleness` must ride the metrics.jsonl timeline."""

    def test_async_gauges_ride_the_timeline(self, tmp_path,
                                            enabled_registry):
        from bflc_demo_tpu.comm.ledger_service import LedgerServer
        from bflc_demo_tpu.utils.serialization import pack_pytree
        cfg = _async_cfg()
        server = LedgerServer(
            cfg, pack_pytree({"W": np.zeros((5, 2), np.float32)}),
            require_auth=False, stall_timeout_s=3600.0)
        try:
            addrs = [f"a{i}" for i in range(cfg.client_num)]
            for a in addrs:
                assert server._dispatch("register", {"addr": a})["ok"]
            committee = set(
                server._dispatch("committee", {})["committee"])
            trainers = [a for a in addrs if a not in committee]
            # process-global registry: assert deltas, not absolutes
            def _stale_count():
                m = obs_metrics.REGISTRY.snapshot()["metrics"].get(
                    "async_admitted_staleness") or {}
                return sum(s["count"] for s in m.get("samples", []))

            def _aggs():
                m = obs_metrics.REGISTRY.snapshot()["metrics"].get(
                    "async_aggregations_total") or {}
                return sum(s["value"] for s in m.get("samples", []))

            stale0, aggs0 = _stale_count(), _aggs()
            # two admissions: buffer below K, depth visible at scrape
            for i, a in enumerate(trainers[:2]):
                assert _async_aupload(server, a, i, 0)["ok"]
            jsonl = str(tmp_path / "metrics.jsonl")
            coll = FleetCollector(
                {"writer": (server.host, server.port)},
                jsonl_path=jsonl)
            server.start()
            rec = coll.scrape(tag="mid-buffer")
            w = rec["roles"]["writer"]["metrics"]
            depth = w["async_buffer_depth"]["samples"][0]["value"]
            assert depth == 2
            assert _stale_count() == stale0 + 2
            # the K-th admission drains inside the ack; next scrape
            # shows the aggregation counter and an empty buffer
            assert _async_aupload(server, trainers[2], 2, 0)["ok"]
            rec2 = coll.scrape(tag="post-drain")
            w2 = rec2["roles"]["writer"]["metrics"]
            assert _aggs() == aggs0 + 1
            assert "async_aggregations_total" in w2
            assert w2["async_buffer_depth"]["samples"][0]["value"] == 0
            # both scrapes landed on the jsonl timeline with the async
            # series present
            tl = load_timeline(jsonl)
            tags = [r["tag"] for r in tl if r["type"] == "scrape"]
            assert tags == ["mid-buffer", "post-drain"]
            for r in tl:
                assert "async_buffer_depth" in \
                    r["roles"]["writer"]["metrics"]
        finally:
            server.close()

    def test_flight_dump_parses_after_mid_drain_kill(self, tmp_path):
        """SIGKILL an async writer that is continuously admitting and
        draining; its flight dump and metrics snapshot must still
        parse and carry the async evidence (the flight recorder's
        durability contract, extended to async mode)."""
        code = textwrap.dedent(f"""
            import numpy as np
            from bflc_demo_tpu import obs
            from bflc_demo_tpu.comm.ledger_service import LedgerServer
            from bflc_demo_tpu.protocol.constants import ProtocolConfig
            from bflc_demo_tpu.utils.serialization import pack_pytree
            import hashlib
            obs.install_process_telemetry(
                "asyncwriter", {str(tmp_path)!r}, interval_s=0.1)
            cfg = ProtocolConfig(
                client_num=6, comm_count=2, aggregate_count=2,
                needed_update_count=3, learning_rate=0.05,
                batch_size=16, async_buffer=3,
                max_staleness=4).validate()
            srv = LedgerServer(
                cfg, pack_pytree({{"W": np.zeros((5, 2), np.float32)}}),
                require_auth=False, stall_timeout_s=3600.0)
            addrs = [f"a{{i}}" for i in range(6)]
            for a in addrs:
                srv._dispatch("register", {{"addr": a}})
            committee = set(srv._dispatch("committee", {{}})["committee"])
            trainers = [a for a in addrs if a not in committee]
            j = 0
            while True:             # admit/drain forever, until killed
                for a in trainers[:3]:
                    ep = srv.ledger.epoch
                    blob = pack_pytree(
                        {{"W": np.full((5, 2), 0.01 * (j % 7),
                                       np.float32)}})
                    d = hashlib.sha256(blob).digest()
                    srv._dispatch("aupload", {{
                        "addr": a, "blob": blob, "hash": d.hex(),
                        "n": 10, "cost": 1.0, "base_epoch": ep}})
                    j += 1
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("BFLC_HEALTH_LEGACY", None)
        p = subprocess.Popen([sys.executable, "-c", code], env=env,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        fpath = tmp_path / "asyncwriter.flight.jsonl"
        deadline = time.monotonic() + 60.0
        drained = False
        try:
            while time.monotonic() < deadline:
                try:
                    dump = load_flight(str(fpath))
                    if any(e.get("name") == "async_round_committed"
                           for e in dump["events"]):
                        drained = True
                        break
                except (OSError, ValueError):
                    pass
                time.sleep(0.1)
            assert drained, "writer never drained a buffer"
        finally:
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=10)
        dump = load_flight(str(fpath))      # parses after SIGKILL
        assert dump["header"]["role"] == "asyncwriter"
        assert any(e.get("name") == "async_round_committed"
                   for e in dump["events"])
        snap = read_snapshot_file(
            str(tmp_path / "asyncwriter.metrics.json"))
        assert snap is not None
        aggs = snap["metrics"]["async_aggregations_total"]["samples"]
        assert aggs and aggs[0]["value"] >= 1
        # the health plane rode along: verdict metrics + health.jsonl
        assert "health_verdict" in snap["metrics"]
        hpath = tmp_path / "asyncwriter.health.jsonl"
        recs = [json.loads(ln) for ln in open(hpath)]
        assert recs and all(r["mode"] == "async" for r in recs)
        assert all("staleness" in r for r in recs)


class TestObserveFaultTimestamps:
    def test_schedule_relative_t_cannot_clobber_wall_clock(self,
                                                           tmp_path):
        """A chaos FaultEvent's 't' is seconds-from-campaign-t0; the
        timeline record's 't' must stay wall-clock or every fault sorts
        to the dawn of the merged timeline (review finding)."""
        jsonl = str(tmp_path / "m.jsonl")
        coll = FleetCollector({}, jsonl_path=jsonl)
        coll.observe_fault({"t": 6.0, "kind": "kill",
                            "target": "writer", "executed": True})
        coll.note("round_commit", epoch=0)
        recs = load_timeline(jsonl)
        fault, note = recs[0], recs[1]
        assert fault["t_sched"] == 6.0
        assert fault["t"] > 1e9                 # wall clock, not 6.0
        assert abs(fault["t"] - note["t"]) < 60.0


class TestCollectorUnderFaults:
    def test_partial_scrape_with_drops_delays_and_a_kill(
            self, tmp_path, enabled_registry):
        """Satellite drill: scrape while the chaos injector drops/delays
        frames to one validator, then kill another validator mid-scrape
        — every scrape must return (partial), never raise."""
        from bflc_demo_tpu.chaos.hooks import install_injector
        from bflc_demo_tpu.comm import wire

        cfg, server, nodes, client = _mini_control_plane()
        jsonl = str(tmp_path / "metrics.jsonl")
        try:
            coll = FleetCollector(
                {"writer": (server.host, server.port),
                 **{f"validator-{i}": (v.host, v.port)
                    for i, v in enumerate(nodes)}},
                # an expected-but-absent file role degrades too
                {"client-ghost": str(tmp_path / "nope.metrics.json")},
                jsonl_path=jsonl, timeout_s=2.0)
            # injector in THIS process, scoped to validator-0's port:
            # the collector's own frames to it are dropped; delay
            # windows cover validator-1 (slow but answering)
            install_injector({
                "t0": time.time(), "role": "collector", "seed": 1,
                "windows": [
                    {"start": -1.0, "end": 600.0, "mode": "drop",
                     "ports": [nodes[0].port], "p": 1.0, "delay_ms": 0},
                    {"start": -1.0, "end": 600.0, "mode": "delay",
                     "ports": [nodes[1].port], "p": 1.0,
                     "delay_ms": 20.0},
                ]})
            try:
                rec = coll.scrape(tag="under-fire")
                assert "validator-0" in rec["coverage"]["missing"]
                assert "client-ghost" in rec["coverage"]["missing"]
                assert "validator-1" in rec["roles"]    # delayed, alive
                assert "writer" in rec["roles"]
                # kill validator-2 between scrapes ("mid-scrape" from
                # the fleet's perspective) — next scrape stays partial
                nodes[2].close()
                rec2 = coll.scrape(tag="after-kill")
                assert "validator-2" in rec2["coverage"]["missing"]
                assert "writer" in rec2["roles"]
            finally:
                install_injector(None)
                wire.set_fault_injector(None)
            # the artifact recorded both partial scrapes
            tl = load_timeline(jsonl)
            assert [r["tag"] for r in tl if r["type"] == "scrape"] == \
                ["under-fire", "after-kill"]
            report = coll.coverage_report()
            assert 0.0 < report["coverage"] < 1.0
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()
