"""Fleet telemetry plane (bflc_demo_tpu.obs): metrics registry semantics,
thread-local tracer spans, flight-recorder durability past SIGKILL, the
telemetry scrape RPC + FleetCollector, and collector degradation under
wire faults (the observability PR's contract: the plane keeps observing
exactly when the fleet is failing).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs.collector import (FleetCollector, load_timeline,
                                         publish_snapshot,
                                         read_snapshot_file)
from bflc_demo_tpu.obs.flight import FlightRecorder, load_flight
from bflc_demo_tpu.obs.metrics import MetricsRegistry, to_prometheus
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils import tracing


class TestMetricsRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry(enabled=True, role="t")
        c = reg.counter("reqs_total", "requests", ("method",))
        c.inc(method="upload")
        c.inc(2.5, method="upload")
        c.inc(method="info")
        g = reg.gauge("round")
        g.set(7)
        g.inc(); g.dec(2)
        h = reg.histogram("lat", "latency", buckets=(0.01, 0.1, 1.0))
        h.observe(0.05)
        h.observe(50.0)
        snap = reg.snapshot()
        json.dumps(snap)                    # JSON-able end to end
        m = snap["metrics"]
        by_label = {s["labels"]["method"]: s["value"]
                    for s in m["reqs_total"]["samples"]}
        assert by_label == {"upload": 3.5, "info": 1.0}
        assert m["round"]["samples"][0]["value"] == 6.0
        hs = m["lat"]["samples"][0]
        assert hs["count"] == 2 and hs["sum"] == pytest.approx(50.05)
        # buckets are CUMULATIVE (Prometheus convention): +Inf == count
        assert hs["buckets"]["+Inf"] == 2
        assert hs["buckets"]["0.1"] == 1

    def test_timer_context_manager(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("dur", "", ("k",))
        with h.time(k="a"):
            time.sleep(0.01)
        s = reg.snapshot()["metrics"]["dur"]["samples"][0]
        assert s["count"] == 1 and s["sum"] >= 0.008

    def test_bounded_cardinality_folds_to_overflow(self):
        reg = MetricsRegistry(enabled=True)
        c = reg.counter("x", "", ("k",))
        for i in range(300):
            c.inc(k=str(i))
        snap = reg.snapshot()
        samples = snap["metrics"]["x"]["samples"]
        assert len(samples) <= reg.max_series_per_metric + 1
        assert snap["series_dropped"] > 0
        overflow = [s for s in samples
                    if s["labels"].get("overflow") == "true"]
        assert overflow and overflow[0]["value"] > 0

    def test_disabled_registry_is_noop(self):
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("y")
        h = reg.histogram("z")
        c.inc()
        h.observe(1.0)
        with h.time():
            pass
        snap = reg.snapshot()
        assert snap["metrics"]["y"]["samples"] == []
        assert snap["metrics"]["z"]["samples"] == []

    def test_redeclaration_idempotent_but_conflicts_raise(self):
        reg = MetricsRegistry(enabled=True)
        a = reg.counter("n", "h", ("k",))
        assert reg.counter("n", "h", ("k",)) is a
        with pytest.raises(ValueError):
            reg.gauge("n")
        with pytest.raises(ValueError):
            reg.counter("n", "h", ("other",))

    def test_snapshot_absorbs_tracer_costs(self):
        reg = MetricsRegistry(enabled=True)
        saved = tracing.PROC.enabled
        tracing.PROC.enabled = True
        try:
            tracing.PROC.charge("test.category_s", 1.25)
            snap = reg.snapshot()
            assert snap["trace_costs"]["test.category_s"] == 1.25
        finally:
            tracing.PROC.enabled = saved
            with tracing.PROC._lock:
                tracing.PROC.costs.pop("test.category_s", None)

    def test_prometheus_text_format(self):
        reg = MetricsRegistry(enabled=True, role="writer")
        reg.counter("ops_total", "ops", ("kind",)).inc(3, kind="up")
        reg.histogram("lat", "", buckets=(0.1,)).observe(0.05)
        text = to_prometheus([reg.snapshot()])
        assert '# TYPE bflc_ops_total counter' in text
        assert 'bflc_ops_total{kind="up",role="writer"} 3.0' in text
        assert 'bflc_lat_bucket{le="0.1",role="writer"} 1' in text
        assert 'bflc_lat_count{role="writer"} 1' in text


class TestTracerThreadLocalSpans:
    """Satellite regression: `Tracer.span` used to share ONE name stack
    across threads (utils/tracing.py documented the hazard) — two
    threads nesting spans interleaved their path prefixes.  The stack is
    now thread-local: every span path must be built from its own
    thread's ancestry only."""

    def test_two_threads_produce_uncrossed_span_paths(self):
        tr = tracing.Tracer(enabled=True)
        start = threading.Barrier(2)

        def worker(name):
            start.wait()
            for _ in range(50):
                with tr.span(f"outer-{name}"):
                    with tr.span(f"inner-{name}"):
                        time.sleep(0)       # force interleaving

        ts = [threading.Thread(target=worker, args=(n,))
              for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        paths = {e["name"] for e in tr.events if e["type"] == "span"}
        assert paths == {"outer-a", "outer-a/inner-a",
                         "outer-b", "outer-b/inner-b"}, paths

    def test_nested_path_still_builds_within_one_thread(self):
        tr = tracing.Tracer(enabled=True)
        with tr.span("a"):
            with tr.span("b"):
                tr.event("e")
        names = [e["name"] for e in tr.events]
        assert "a/b/e" in names and "a/b" in names and "a" in names


class TestFlightRecorder:
    def test_sigkill_leaves_parseable_dump(self, tmp_path):
        """The chaos contract: a SIGKILLed role's flight file exists and
        parses (periodic flush + atomic rename — no torn files)."""
        code = textwrap.dedent(f"""
            import time
            from bflc_demo_tpu import obs
            from bflc_demo_tpu.obs import flight
            obs.install_process_telemetry(
                "victim", {str(tmp_path)!r}, interval_s=0.1)
            for i in range(10_000):
                flight.FLIGHT.record("event", "tick", i=i)
                time.sleep(0.01)
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen([sys.executable, "-c", code], env=env,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        path = tmp_path / "victim.flight.jsonl"
        deadline = time.monotonic() + 30.0
        # wait until the victim demonstrably recorded some ticks
        while time.monotonic() < deadline:
            try:
                if len(load_flight(str(path))["events"]) >= 3:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)
        dump = load_flight(str(path))
        assert dump["header"]["role"] == "victim"
        ticks = [e for e in dump["events"] if e["name"] == "tick"]
        assert len(ticks) >= 3
        # the metrics snapshot file was published too
        snap = read_snapshot_file(str(tmp_path / "victim.metrics.json"))
        assert snap is not None and snap["role"] == "victim"

    def test_ring_is_bounded_and_flush_atomic(self, tmp_path):
        rec = FlightRecorder(capacity=16)
        rec.enabled = True
        rec.path = str(tmp_path / "r.flight.jsonl")
        for i in range(100):
            rec.record("event", "e", i=i)
        assert rec.flush("test")
        dump = load_flight(rec.path)
        assert dump["header"]["reason"] == "test"
        assert len(dump["events"]) == 16
        assert dump["events"][-1]["i"] == 99      # newest survives

    def test_load_flight_rejects_headerless_garbage(self, tmp_path):
        p = tmp_path / "bad.flight.jsonl"
        p.write_text('{"no": "header"}\n')
        with pytest.raises(ValueError):
            load_flight(str(p))


def _mini_control_plane(n_clients=4, validators=4):
    """Writer + validator fleet, thread-served in this process, one
    complete protocol round driven through the socket (the
    profile_round topology, shrunk)."""
    import hashlib
    import struct

    from bflc_demo_tpu.comm.bft import ValidatorNode, provision_validators
    from bflc_demo_tpu.comm.identity import _op_bytes, provision_wallets
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    from bflc_demo_tpu.utils.serialization import pack_pytree

    cfg = ProtocolConfig(client_num=n_clients, comm_count=2,
                         aggregate_count=2, needed_update_count=2,
                         learning_rate=0.05, batch_size=16)
    wallets, _ = provision_wallets(n_clients, b"obs-test-seed-000001")
    vwallets, vkeys = provision_validators(validators,
                                           b"obs-test-validators-01")
    blob0 = pack_pytree({"W": np.zeros((5, 2), np.float32)})
    nodes = [ValidatorNode(cfg, w, i, validator_keys=vkeys)
             for i, w in enumerate(vwallets)]
    for v in nodes:
        v.start()
    server = LedgerServer(cfg, blob0,
                          bft_validators=[(v.host, v.port)
                                          for v in nodes],
                          bft_keys=vkeys)
    server.start()
    client = CoordinatorClient(server.host, server.port)

    def sign(w, kind, epoch, payload):
        return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()

    for w in wallets:
        r = client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=sign(w, "register", 0, b""))
        assert r["ok"], r
    committee = set(client.request("committee")["committee"])
    trainers = [w for w in wallets if w.address not in committee]
    for i, w in enumerate(trainers[:2]):
        blob = pack_pytree({"W": np.full((5, 2), 0.1 * (i + 1),
                                         np.float32)})
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", 10 + i, 1.0)
        r = client.request("upload", addr=w.address, blob=blob,
                           hash=digest.hex(), n=10 + i, cost=1.0,
                           epoch=0, tag=sign(w, "upload", 0, payload))
        assert r["ok"], r
    return cfg, server, nodes, client


@pytest.fixture
def enabled_registry():
    """Flip the process registry on for the test, restore after (it is
    process-global state)."""
    saved_enabled = obs_metrics.REGISTRY.enabled
    saved_role = obs_metrics.REGISTRY.role
    obs_metrics.REGISTRY.enabled = True
    try:
        yield obs_metrics.REGISTRY
    finally:
        obs_metrics.REGISTRY.enabled = saved_enabled
        obs_metrics.REGISTRY.role = saved_role


class TestTelemetryRPCAndCollector:
    def test_scrape_all_roles_jsonl_prom_and_fleet_top(
            self, tmp_path, enabled_registry):
        cfg, server, nodes, client = _mini_control_plane()
        try:
            jsonl = str(tmp_path / "metrics.jsonl")
            # a file-published role rides the same scrape (what clients
            # and standbys do in the process federation)
            fpath = str(tmp_path / "client-x.metrics.json")
            assert publish_snapshot(fpath)
            coll = FleetCollector(
                {"writer": (server.host, server.port),
                 **{f"validator-{i}": (v.host, v.port)
                    for i, v in enumerate(nodes)}},
                {"client-x": fpath}, jsonl_path=jsonl)
            coll.note("round_commit", epoch=0)
            rec = coll.scrape(tag="round-0")
            assert rec["coverage"]["answered"] == 6
            assert rec["coverage"]["missing"] == []
            wsnap = rec["roles"]["writer"]
            # writer gauges sampled at scrape time
            names = set(wsnap["metrics"])
            assert {"round", "uncertified_backlog",
                    "rpc_latency_seconds"} <= names
            # validators answered with their own metrics + role
            vsnap = rec["roles"]["validator-0"]
            assert "vote_latency_seconds" in vsnap["metrics"]
            # tracer costs absorbed into the snapshot
            assert isinstance(wsnap["trace_costs"], dict)

            # artifacts: jsonl timeline + Prometheus dump
            prom = str(tmp_path / "metrics.prom")
            assert coll.write_prometheus(prom)
            text = open(prom).read()
            assert "bflc_rpc_latency_seconds" in text
            assert 'role="writer"' in text
            tl = load_timeline(jsonl)
            assert [r["type"] for r in tl] == ["note", "scrape"]

            # fleet_top renders both views without raising
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))), "tools"))
            try:
                import fleet_top
            finally:
                sys.path.pop(0)
            once = fleet_top.render_once(tl)
            assert "writer" in once and "validator-0" in once
            timeline = fleet_top.render_timeline(tl)
            assert "round_commit" in timeline
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()

    def test_wire_frame_mix_counted(self, tmp_path, enabled_registry):
        cfg, server, nodes, client = _mini_control_plane()
        try:
            coll = FleetCollector({"writer": (server.host, server.port)})
            rec = coll.scrape()
            frames = rec["roles"]["writer"]["metrics"][
                "wire_frames_total"]["samples"]
            kinds = {(s["labels"]["dir"], s["labels"]["kind"]):
                     s["value"] for s in frames}
            # uploads carried binary blob frames; control replies are json
            assert kinds.get(("in", "bin"), 0) >= 1
            assert kinds.get(("out", "json"), 0) >= 1
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()


class TestTraceZeroOverheadWhenOff:
    """Sampling disabled (the default) must mean literally nothing on
    the hot path: no span records, no context, no `_tp` wire bytes, and
    the null span a shared singleton (no per-call allocation)."""

    def test_disabled_recorder_allocates_and_sends_nothing(self):
        from bflc_demo_tpu.comm import wire
        from bflc_demo_tpu.obs import trace as obs_trace
        t = obs_trace.TRACE
        assert not t.enabled            # default in the test process
        before = len(t._ring)
        with t.start_trace("root", epoch=1) as sp:
            sp["attr"] = "ignored"
            with t.span("child"):
                assert t.current_traceparent() is None
        assert len(t._ring) == before
        # the null span is ONE object, returned by every entry point
        assert t.span("a") is t.start_trace("b") \
            is t.span_from(None, "c") \
            is obs_trace.server_span({"_tp": "x"}, "d")
        # and the wire encoding is byte-identical to an untraced sender
        with t.start_trace("root"):
            assert wire._encode({"method": "m"}) == b'{"method":"m"}'

    def test_upload_lag_histogram_writer_side(self, enabled_registry):
        """Straggler-evidence satellite: every admitted upload observes
        its lag behind the round's first admitted upload into
        `upload_lag_seconds` (the async-aggregation baseline metric),
        exported via the existing scrape."""
        def lag_sample():
            snap = obs_metrics.REGISTRY.snapshot()
            m = snap["metrics"].get("upload_lag_seconds")
            return (m or {}).get("samples") or [{"count": 0, "sum": 0.0}]

        before = lag_sample()[0]["count"]
        cfg, server, nodes, client = _mini_control_plane()
        try:
            s = lag_sample()[0]
            # the mini plane admitted two uploads in epoch 0: the first
            # observes lag 0, the second a tiny positive lag
            assert s["count"] == before + 2
            assert s["sum"] < 5.0       # both lags are sub-second
            rec = FleetCollector(
                {"writer": (server.host, server.port)}).scrape()
            assert "upload_lag_seconds" in \
                rec["roles"]["writer"]["metrics"]
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()


class TestObserveFaultTimestamps:
    def test_schedule_relative_t_cannot_clobber_wall_clock(self,
                                                           tmp_path):
        """A chaos FaultEvent's 't' is seconds-from-campaign-t0; the
        timeline record's 't' must stay wall-clock or every fault sorts
        to the dawn of the merged timeline (review finding)."""
        jsonl = str(tmp_path / "m.jsonl")
        coll = FleetCollector({}, jsonl_path=jsonl)
        coll.observe_fault({"t": 6.0, "kind": "kill",
                            "target": "writer", "executed": True})
        coll.note("round_commit", epoch=0)
        recs = load_timeline(jsonl)
        fault, note = recs[0], recs[1]
        assert fault["t_sched"] == 6.0
        assert fault["t"] > 1e9                 # wall clock, not 6.0
        assert abs(fault["t"] - note["t"]) < 60.0


class TestCollectorUnderFaults:
    def test_partial_scrape_with_drops_delays_and_a_kill(
            self, tmp_path, enabled_registry):
        """Satellite drill: scrape while the chaos injector drops/delays
        frames to one validator, then kill another validator mid-scrape
        — every scrape must return (partial), never raise."""
        from bflc_demo_tpu.chaos.hooks import install_injector
        from bflc_demo_tpu.comm import wire

        cfg, server, nodes, client = _mini_control_plane()
        jsonl = str(tmp_path / "metrics.jsonl")
        try:
            coll = FleetCollector(
                {"writer": (server.host, server.port),
                 **{f"validator-{i}": (v.host, v.port)
                    for i, v in enumerate(nodes)}},
                # an expected-but-absent file role degrades too
                {"client-ghost": str(tmp_path / "nope.metrics.json")},
                jsonl_path=jsonl, timeout_s=2.0)
            # injector in THIS process, scoped to validator-0's port:
            # the collector's own frames to it are dropped; delay
            # windows cover validator-1 (slow but answering)
            install_injector({
                "t0": time.time(), "role": "collector", "seed": 1,
                "windows": [
                    {"start": -1.0, "end": 600.0, "mode": "drop",
                     "ports": [nodes[0].port], "p": 1.0, "delay_ms": 0},
                    {"start": -1.0, "end": 600.0, "mode": "delay",
                     "ports": [nodes[1].port], "p": 1.0,
                     "delay_ms": 20.0},
                ]})
            try:
                rec = coll.scrape(tag="under-fire")
                assert "validator-0" in rec["coverage"]["missing"]
                assert "client-ghost" in rec["coverage"]["missing"]
                assert "validator-1" in rec["roles"]    # delayed, alive
                assert "writer" in rec["roles"]
                # kill validator-2 between scrapes ("mid-scrape" from
                # the fleet's perspective) — next scrape stays partial
                nodes[2].close()
                rec2 = coll.scrape(tag="after-kill")
                assert "validator-2" in rec2["coverage"]["missing"]
                assert "writer" in rec2["roles"]
            finally:
                install_injector(None)
                wire.set_fault_injector(None)
            # the artifact recorded both partial scrapes
            tl = load_timeline(jsonl)
            assert [r["tag"] for r in tl if r["type"] == "scrape"] == \
                ["under-fire", "after-kill"]
            report = coll.coverage_report()
            assert 0.0 < report["coverage"] < 1.0
        finally:
            client.close()
            server.close()
            for v in nodes:
                v.close()
