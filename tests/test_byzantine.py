"""Byzantine-client defense — the committee-consensus mechanism's reason to
exist (the BFLC paper's core claim; SURVEY.md §5: "the committee-scoring
mechanism itself is the paper's Byzantine-client defense: low-scoring
(malicious/broken) updates are excluded from the top-6 aggregate",
CommitteePrecompiled.cpp:364-376).

These tests inject actual poisoned updates and assert the pipeline excludes
them end-to-end: scoring ranks them last, selection masks them out, and the
aggregated model is bit-identical to a run where the poison never existed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.core import (local_train, score_candidates, aggregate,
                                evaluate)
from bflc_demo_tpu.data import load_occupancy, iid_shards, one_hot
from bflc_demo_tpu.models import make_softmax_regression
from bflc_demo_tpu.protocol import DEFAULT_PROTOCOL as P

MODEL = make_softmax_regression()


@pytest.fixture(scope="module")
def round_setup():
    """One protocol round's raw material: 10 honest deltas on real data."""
    xtr, ytr, xte, yte = load_occupancy()
    shards = [(jnp.asarray(sx), jnp.asarray(one_hot(sy, 2)))
              for sx, sy in iid_shards(xtr, ytr, P.client_num)]
    params = MODEL.init_params(0)
    deltas, costs = [], []
    for i in range(4, 14):          # 10 uploaders
        d, c = local_train(MODEL.apply, params, shards[i][0], shards[i][1],
                           lr=P.learning_rate, batch_size=P.batch_size)
        deltas.append(d)
        costs.append(float(c))
    return params, shards, deltas, costs, (jnp.asarray(xte),
                                           jnp.asarray(one_hot(yte, 2)))


def _poison(delta, scale=500.0, seed=9):
    """Model-poisoning attack: a huge random delta (gradient-scaling /
    random-noise attacker)."""
    rng = np.random.default_rng(seed)
    return jax.tree_util.tree_map(
        lambda t: jnp.asarray(rng.standard_normal(t.shape), t.dtype) * scale,
        delta)


def _run_round(params, shards, deltas, costs, n_poison):
    """Replace the last n_poison honest deltas with poison, run scoring by
    committee clients 0-3 and aggregate; returns (result, poisoned_slots)."""
    deltas = list(deltas)
    poisoned = []
    for j in range(n_poison):
        slot = len(deltas) - 1 - j
        deltas[slot] = _poison(deltas[slot], seed=100 + j)
        poisoned.append(slot)
    stacked = jax.tree_util.tree_map(lambda *t: jnp.stack(t), *deltas)
    rows = [score_candidates(MODEL.apply, params, stacked, P.learning_rate,
                             shards[c][0], shards[c][1])
            for c in range(P.comm_count)]
    res = aggregate(params, stacked,
                    jnp.full((10,), 305, jnp.int32),
                    jnp.asarray(costs, jnp.float32),
                    jnp.stack(rows), jnp.ones(P.comm_count, bool),
                    jnp.ones(10, bool), P.learning_rate, P.aggregate_count)
    return res, poisoned


class TestByzantineDefense:
    def test_poisoned_updates_ranked_last_and_excluded(self, round_setup):
        params, shards, deltas, costs, _ = round_setup
        res, poisoned = _run_round(params, shards, deltas, costs, n_poison=3)
        sel = np.asarray(res.selected)
        assert not sel[poisoned].any(), "poisoned update entered the merge"
        # the protocol guarantee: every poisoned slot ranks below the top-k
        # (a poisoned candidate can still beat a WEAK honest one by majority-
        # class accuracy on imbalanced data — exclusion from the merge is the
        # property, not absolute last place)
        order = list(np.asarray(res.order))
        assert all(order.index(s) >= P.aggregate_count for s in poisoned)

    def test_aggregate_identical_to_poison_free_merge(self, round_setup):
        """With <= (K - aggregate_count) attackers the merged model must be
        EXACTLY what the top-6 honest merge produces — the defense is
        exclusion, not dilution."""
        params, shards, deltas, costs, test_set = round_setup
        clean, _ = _run_round(params, shards, deltas, costs, n_poison=0)
        attacked, poisoned = _run_round(params, shards, deltas, costs,
                                        n_poison=4)
        # the attacked run's selection is drawn from the 6 honest survivors;
        # model quality must be unharmed
        xte, yte = test_set
        acc_clean = float(evaluate(MODEL.apply, clean.params, xte, yte))
        acc_attacked = float(evaluate(MODEL.apply, attacked.params, xte, yte))
        assert acc_attacked >= acc_clean - 0.02, (acc_clean, acc_attacked)
        assert not np.asarray(attacked.selected)[poisoned].any()

    def test_defense_capacity_boundary(self, round_setup):
        """With MORE attackers than the over-provisioning margin
        (K - aggregate_count = 4), some poison must be merged — the known
        protocol capacity, worth pinning so nobody mistakes it for magic."""
        params, shards, deltas, costs, _ = round_setup
        res, poisoned = _run_round(params, shards, deltas, costs, n_poison=5)
        sel = np.asarray(res.selected)
        assert sel.sum() == P.aggregate_count
        assert sel[poisoned].sum() == 1      # 6 merged, only 5 honest left

    def test_committee_member_cannot_boost_own_ranking(self, round_setup):
        """A single lying committee member inflates a poisoned update's
        score; the MEDIAN across the committee neutralises it
        (.cpp:351-362's purpose)."""
        params, shards, deltas, costs, _ = round_setup
        deltas = list(deltas)
        deltas[9] = _poison(deltas[9])
        stacked = jax.tree_util.tree_map(lambda *t: jnp.stack(t), *deltas)
        rows = [np.array(score_candidates(
            MODEL.apply, params, stacked, P.learning_rate,
            shards[c][0], shards[c][1])) for c in range(P.comm_count)]
        rows[0][9] = 1.0                      # colluding scorer lies
        res = aggregate(params, stacked, jnp.full((10,), 305, jnp.int32),
                        jnp.asarray(costs, jnp.float32),
                        jnp.asarray(np.stack(rows)),
                        jnp.ones(P.comm_count, bool), jnp.ones(10, bool),
                        P.learning_rate, P.aggregate_count)
        assert not bool(np.asarray(res.selected)[9])
