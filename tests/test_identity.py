"""Authenticated-ledger tests: identity provisioning, MAC verification,
replay rejection, asymmetric (Ed25519) identity, full authenticated round."""

import numpy as np
import pytest

from bflc_demo_tpu.comm.identity import (KeyRing, AuthenticatedLedger,
                                         Wallet, PublicDirectory,
                                         provision_wallets, address_of,
                                         sign_register, sign_upload,
                                         sign_scores, _op_bytes)
from bflc_demo_tpu.ledger import make_ledger, LedgerStatus
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3)


def addr(i):
    return f"0x{i:03x}"


@pytest.fixture
def auth_led():
    keys = KeyRing(b"master-seed-0123456789abcdef")
    led = AuthenticatedLedger(make_ledger(CFG, backend="python"), keys)
    return led, keys


class TestIdentity:
    def test_keyring_deterministic_distinct(self):
        k = KeyRing(b"master-seed-0123456789abcdef")
        assert k.secret_for("0x001") == k.secret_for("0x001")
        assert k.secret_for("0x001") != k.secret_for("0x002")
        with pytest.raises(ValueError):
            KeyRing(b"short")

    def test_valid_round_trip(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            st = led.register_node(addr(i), sign_register(keys, addr(i)))
            assert st == LedgerStatus.OK
        assert led.epoch == 0
        st = led.upload_local_update(
            addr(3), b"\1" * 32, 100, 1.5, 0,
            sign_upload(keys, addr(3), b"\1" * 32, 100, 1.5, 0))
        assert st == LedgerStatus.OK

    def test_wrong_key_rejected(self, auth_led):
        led, _ = auth_led
        impostor = KeyRing(b"some-other-master-seed-xxxxx")
        st = led.register_node(addr(0), sign_register(impostor, addr(0)))
        assert st == LedgerStatus.BAD_ARG
        assert led.num_registered == 0

    def test_tag_bound_to_content(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        tag = sign_upload(keys, addr(3), b"\1" * 32, 100, 1.5, 0)
        # altered payload under the same tag
        st = led.upload_local_update(addr(3), b"\2" * 32, 100, 1.5, 0, tag)
        assert st == LedgerStatus.BAD_ARG
        # altered epoch under the same tag
        st = led.upload_local_update(addr(3), b"\1" * 32, 100, 1.5, 1, tag)
        assert st == LedgerStatus.BAD_ARG
        # sender substitution: client 4 replaying client 3's tag
        st = led.upload_local_update(addr(4), b"\1" * 32, 100, 1.5, 0, tag)
        assert st == LedgerStatus.BAD_ARG
        assert led.update_count == 0

    def test_replay_rejected(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        tag = sign_upload(keys, addr(3), b"\1" * 32, 100, 1.5, 0)
        assert led.upload_local_update(addr(3), b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.OK
        # an eavesdropper replaying the exact same authenticated op
        assert led.upload_local_update(addr(3), b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.DUPLICATE

    def test_retry_after_transient_rejection_allowed(self, auth_led):
        """A tag is consumed only when the op is ACCEPTED: scores rejected
        as NOT_READY (round under-filled) may be resent with the same MAC
        once close_round opens the way."""
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        for i in (2, 3):     # only 2 of the needed 3 updates arrive
            h = bytes([i]) * 32
            led.upload_local_update(
                addr(i), h, 100, 1.0, 0,
                sign_upload(keys, addr(i), h, 100, 1.0, 0))
        comm = led.committee()[0]
        scores = [0.5, 0.7]
        tag = sign_scores(keys, comm, 0, scores)
        assert led.upload_scores(comm, 0, scores, tag) == \
            LedgerStatus.NOT_READY
        assert led.close_round() == LedgerStatus.OK
        assert led.upload_scores(comm, 0, scores, tag) == LedgerStatus.OK

    def test_threaded_runtime_authenticated(self):
        """The concurrent runtime with a keyring: every client op carries a
        MAC through the locked transport boundary and the run converges."""
        from bflc_demo_tpu.client.threaded import ThreadedFederation
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.models import make_softmax_regression
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:2000], ytr[:2000], CFG.client_num)
        fed = ThreadedFederation(
            make_softmax_regression(), shards, (xte[:500], yte[:500]), CFG,
            keyring=KeyRing(b"threaded-master-seed-123456"))
        res = fed.run(rounds=2, timeout_s=120)
        assert res.rounds_completed == 2
        assert res.ledger.verify_log()

    def test_wallet_sign_verify_and_forgery(self):
        """Ed25519: the directory verifies genuine tags and rejects forgeries;
        critically, the VERIFIER holds only public keys, so unlike the HMAC
        keyring it cannot fabricate a client's tag (the round-1 weakness the
        reference's ECDSA model never had)."""
        wallets, directory = provision_wallets(3, b"ed-master-seed-000001")
        w = wallets[0]
        ob = _op_bytes("upload", w.address, 0, b"\1" * 32)
        tag = w.mac(w.address, ob)
        assert directory.verify(w.address, ob, tag)
        assert not directory.verify(w.address, ob + b"x", tag)
        assert not directory.verify(wallets[1].address, ob, tag)
        assert not directory.verify(w.address, ob, b"\0" * 64)
        # address is self-authenticating: derived from the public key
        assert w.address == address_of(w.public_bytes)
        # a wallet refuses to sign for an address it doesn't own
        with pytest.raises(ValueError):
            w.mac(wallets[1].address, ob)

    def test_wallet_determinism_and_uniqueness(self):
        a = Wallet.from_seed(b"seed-a")
        a2 = Wallet.from_seed(b"seed-a")
        b = Wallet.from_seed(b"seed-b")
        assert a.address == a2.address
        assert a.sign(b"msg") == a2.sign(b"msg")     # RFC 8032 deterministic
        assert a.address != b.address

    def test_pair_secret_agreement(self):
        """X25519: both endpoints derive the same pair secret; different
        pairs and different contexts derive different secrets."""
        wallets, _ = provision_wallets(3, b"dh-master-seed-000001")
        a, b, c = wallets
        s_ab = a.pair_secret(b.dh_public_bytes, context=b"round7")
        s_ba = b.pair_secret(a.dh_public_bytes, context=b"round7")
        assert s_ab == s_ba
        assert s_ab != a.pair_secret(c.dh_public_bytes, context=b"round7")
        assert s_ab != a.pair_secret(b.dh_public_bytes, context=b"round8")

    def test_authenticated_ledger_with_directory(self):
        """The AuthenticatedLedger over a PublicDirectory: wallet-signed ops
        accepted, wrong-wallet and replayed tags rejected — same transport
        contract as the HMAC keyring, stronger trust model."""
        wallets, directory = provision_wallets(
            CFG.client_num, b"dir-master-seed-000001")
        led = AuthenticatedLedger(make_ledger(CFG, backend="python"),
                                  directory)
        for w in wallets:
            st = led.register_node(w.address, sign_register(w, w.address))
            assert st == LedgerStatus.OK
        assert led.epoch == 0
        w = wallets[3]
        tag = sign_upload(w, w.address, b"\1" * 32, 100, 1.5, 0)
        assert led.upload_local_update(w.address, b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.OK
        # replay
        assert led.upload_local_update(w.address, b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.DUPLICATE
        # another wallet cannot sign for w's address
        x = wallets[4]
        forged = x.sign(_op_bytes("upload", w.address, 0, b"\2" * 32 +
                                  __import__("struct").pack("<qd", 50, 1.0)))
        assert led.upload_local_update(w.address, b"\2" * 32, 50, 1.0, 0,
                                       forged) == LedgerStatus.BAD_ARG

    def test_full_authenticated_round(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        for i in (2, 3, 4):
            h = bytes([i]) * 32
            st = led.upload_local_update(
                addr(i), h, 100 + i, 1.0, 0,
                sign_upload(keys, addr(i), h, 100 + i, 1.0, 0))
            assert st == LedgerStatus.OK
        rng = np.random.default_rng(0)
        for c in led.committee():
            scores = [float(s) for s in rng.random(3)]
            st = led.upload_scores(c, 0, scores,
                                   sign_scores(keys, c, 0, scores))
            assert st == LedgerStatus.OK
        assert led.aggregate_ready()
        # coordinator-side ops pass through unauthenticated (writer authority)
        assert led.commit_model(b"\x09" * 32,
                                0) == LedgerStatus.OK
        assert led.epoch == 1
        assert led.verify_log()


class TestPure25519Backend:
    """The from-first-principles Ed25519/X25519 fallback (comm.pure25519)
    must BE the RFC algorithms — pinned against the published test vectors
    — and byte-compatible with the `cryptography` backend wherever both
    exist, so wallets interoperate across hosts."""

    def test_ed25519_rfc8032_vectors(self):
        from bflc_demo_tpu.comm import pure25519 as p
        sk = bytes.fromhex("9d61b19deffd5a60ba844af492ec2cc4"
                           "4449c5697b326919703bac031cae7f60")
        pk = bytes.fromhex("d75a980182b10ab7d54bfed3c964073a"
                           "0ee172f3daa62325af021a68f707511a")
        sig = bytes.fromhex(
            "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249"
            "01555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe2465514143"
            "8e7a100b")
        assert p.ed25519_public(sk) == pk
        assert p.ed25519_sign(sk, b"") == sig
        assert p.ed25519_verify(pk, b"", sig)
        assert not p.ed25519_verify(pk, b"x", sig)
        sk2 = bytes.fromhex("4ccd089b28ff96da9db6c346ec114e0f"
                            "5b8a319f35aba624da8cf6ed4fb8a6fb")
        pk2 = bytes.fromhex("3d4017c3e843895a92b70aa74d1b7ebc"
                            "9c982ccf2ec4968cc0cd55f12af4660c")
        sig2 = bytes.fromhex(
            "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb"
            "69da085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d2916"
            "12bb0c00")
        assert p.ed25519_public(sk2) == pk2
        assert p.ed25519_sign(sk2, b"\x72") == sig2
        # malformed inputs are False, never exceptions
        assert not p.ed25519_verify(b"\xff" * 32, b"", sig)
        assert not p.ed25519_verify(pk, b"", b"\x00" * 64)
        assert not p.ed25519_verify(pk, b"", sig[:-1])

    def test_x25519_rfc7748_vector_and_dh_symmetry(self):
        from bflc_demo_tpu.comm import pure25519 as p
        k = bytes.fromhex("a546e36bf0527c9d3b16154b82465edd"
                          "62144c0ac1fc5a18506a2244ba449ac4")
        u = bytes.fromhex("e6db6867583030db3594c1a424b15f7c"
                          "726624ec26b3353b10a903a6d0ab1c4c")
        out = bytes.fromhex("c3da55379de9c6908e94ea4df28d084f"
                            "32eccf03491c71f754b4075577a28552")
        assert p.x25519_exchange(k, u) == out
        import hashlib
        a = hashlib.sha256(b"dh-a").digest()
        b = hashlib.sha256(b"dh-b").digest()
        assert p.x25519_exchange(a, p.x25519_public(b)) == \
            p.x25519_exchange(b, p.x25519_public(a))

    def test_backends_interoperate_when_both_exist(self):
        from bflc_demo_tpu.comm import identity as idm
        from bflc_demo_tpu.comm import pure25519 as p
        w = Wallet.from_seed(b"xbackend-1")
        msg = b"cross-backend message"
        sig = w.sign(msg)
        # the pure backend verifies whatever the active backend signed
        assert p.ed25519_verify(w.public_bytes, msg, sig)
        # and the chokepoint agrees with it
        assert idm.verify_signature(w.public_bytes, msg, sig)
        if idm.ED25519_BACKEND == "cryptography":
            # same seed -> same keys/sigs under both implementations
            assert p.ed25519_public(w._sign_sk) == w.public_bytes
            assert p.ed25519_sign(w._sign_sk, msg) == sig
            assert p.x25519_public(w._dh_sk) == w.dh_public_bytes
