"""Authenticated-ledger tests: identity provisioning, MAC verification,
replay rejection, asymmetric (Ed25519) identity, full authenticated round."""

import numpy as np
import pytest

from bflc_demo_tpu.comm.identity import (KeyRing, AuthenticatedLedger,
                                         Wallet, PublicDirectory,
                                         provision_wallets, address_of,
                                         sign_register, sign_upload,
                                         sign_scores, _op_bytes)
from bflc_demo_tpu.ledger import make_ledger, LedgerStatus
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3)


def addr(i):
    return f"0x{i:03x}"


@pytest.fixture
def auth_led():
    keys = KeyRing(b"master-seed-0123456789abcdef")
    led = AuthenticatedLedger(make_ledger(CFG, backend="python"), keys)
    return led, keys


class TestIdentity:
    def test_keyring_deterministic_distinct(self):
        k = KeyRing(b"master-seed-0123456789abcdef")
        assert k.secret_for("0x001") == k.secret_for("0x001")
        assert k.secret_for("0x001") != k.secret_for("0x002")
        with pytest.raises(ValueError):
            KeyRing(b"short")

    def test_valid_round_trip(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            st = led.register_node(addr(i), sign_register(keys, addr(i)))
            assert st == LedgerStatus.OK
        assert led.epoch == 0
        st = led.upload_local_update(
            addr(3), b"\1" * 32, 100, 1.5, 0,
            sign_upload(keys, addr(3), b"\1" * 32, 100, 1.5, 0))
        assert st == LedgerStatus.OK

    def test_wrong_key_rejected(self, auth_led):
        led, _ = auth_led
        impostor = KeyRing(b"some-other-master-seed-xxxxx")
        st = led.register_node(addr(0), sign_register(impostor, addr(0)))
        assert st == LedgerStatus.BAD_ARG
        assert led.num_registered == 0

    def test_tag_bound_to_content(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        tag = sign_upload(keys, addr(3), b"\1" * 32, 100, 1.5, 0)
        # altered payload under the same tag
        st = led.upload_local_update(addr(3), b"\2" * 32, 100, 1.5, 0, tag)
        assert st == LedgerStatus.BAD_ARG
        # altered epoch under the same tag
        st = led.upload_local_update(addr(3), b"\1" * 32, 100, 1.5, 1, tag)
        assert st == LedgerStatus.BAD_ARG
        # sender substitution: client 4 replaying client 3's tag
        st = led.upload_local_update(addr(4), b"\1" * 32, 100, 1.5, 0, tag)
        assert st == LedgerStatus.BAD_ARG
        assert led.update_count == 0

    def test_replay_rejected(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        tag = sign_upload(keys, addr(3), b"\1" * 32, 100, 1.5, 0)
        assert led.upload_local_update(addr(3), b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.OK
        # an eavesdropper replaying the exact same authenticated op
        assert led.upload_local_update(addr(3), b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.DUPLICATE

    def test_retry_after_transient_rejection_allowed(self, auth_led):
        """A tag is consumed only when the op is ACCEPTED: scores rejected
        as NOT_READY (round under-filled) may be resent with the same MAC
        once close_round opens the way."""
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        for i in (2, 3):     # only 2 of the needed 3 updates arrive
            h = bytes([i]) * 32
            led.upload_local_update(
                addr(i), h, 100, 1.0, 0,
                sign_upload(keys, addr(i), h, 100, 1.0, 0))
        comm = led.committee()[0]
        scores = [0.5, 0.7]
        tag = sign_scores(keys, comm, 0, scores)
        assert led.upload_scores(comm, 0, scores, tag) == \
            LedgerStatus.NOT_READY
        assert led.close_round() == LedgerStatus.OK
        assert led.upload_scores(comm, 0, scores, tag) == LedgerStatus.OK

    def test_threaded_runtime_authenticated(self):
        """The concurrent runtime with a keyring: every client op carries a
        MAC through the locked transport boundary and the run converges."""
        from bflc_demo_tpu.client.threaded import ThreadedFederation
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.models import make_softmax_regression
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:2000], ytr[:2000], CFG.client_num)
        fed = ThreadedFederation(
            make_softmax_regression(), shards, (xte[:500], yte[:500]), CFG,
            keyring=KeyRing(b"threaded-master-seed-123456"))
        res = fed.run(rounds=2, timeout_s=120)
        assert res.rounds_completed == 2
        assert res.ledger.verify_log()

    def test_wallet_sign_verify_and_forgery(self):
        """Ed25519: the directory verifies genuine tags and rejects forgeries;
        critically, the VERIFIER holds only public keys, so unlike the HMAC
        keyring it cannot fabricate a client's tag (the round-1 weakness the
        reference's ECDSA model never had)."""
        wallets, directory = provision_wallets(3, b"ed-master-seed-000001")
        w = wallets[0]
        ob = _op_bytes("upload", w.address, 0, b"\1" * 32)
        tag = w.mac(w.address, ob)
        assert directory.verify(w.address, ob, tag)
        assert not directory.verify(w.address, ob + b"x", tag)
        assert not directory.verify(wallets[1].address, ob, tag)
        assert not directory.verify(w.address, ob, b"\0" * 64)
        # address is self-authenticating: derived from the public key
        assert w.address == address_of(w.public_bytes)
        # a wallet refuses to sign for an address it doesn't own
        with pytest.raises(ValueError):
            w.mac(wallets[1].address, ob)

    def test_wallet_determinism_and_uniqueness(self):
        a = Wallet.from_seed(b"seed-a")
        a2 = Wallet.from_seed(b"seed-a")
        b = Wallet.from_seed(b"seed-b")
        assert a.address == a2.address
        assert a.sign(b"msg") == a2.sign(b"msg")     # RFC 8032 deterministic
        assert a.address != b.address

    def test_pair_secret_agreement(self):
        """X25519: both endpoints derive the same pair secret; different
        pairs and different contexts derive different secrets."""
        wallets, _ = provision_wallets(3, b"dh-master-seed-000001")
        a, b, c = wallets
        s_ab = a.pair_secret(b.dh_public_bytes, context=b"round7")
        s_ba = b.pair_secret(a.dh_public_bytes, context=b"round7")
        assert s_ab == s_ba
        assert s_ab != a.pair_secret(c.dh_public_bytes, context=b"round7")
        assert s_ab != a.pair_secret(b.dh_public_bytes, context=b"round8")

    def test_authenticated_ledger_with_directory(self):
        """The AuthenticatedLedger over a PublicDirectory: wallet-signed ops
        accepted, wrong-wallet and replayed tags rejected — same transport
        contract as the HMAC keyring, stronger trust model."""
        wallets, directory = provision_wallets(
            CFG.client_num, b"dir-master-seed-000001")
        led = AuthenticatedLedger(make_ledger(CFG, backend="python"),
                                  directory)
        for w in wallets:
            st = led.register_node(w.address, sign_register(w, w.address))
            assert st == LedgerStatus.OK
        assert led.epoch == 0
        w = wallets[3]
        tag = sign_upload(w, w.address, b"\1" * 32, 100, 1.5, 0)
        assert led.upload_local_update(w.address, b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.OK
        # replay
        assert led.upload_local_update(w.address, b"\1" * 32, 100, 1.5, 0,
                                       tag) == LedgerStatus.DUPLICATE
        # another wallet cannot sign for w's address
        x = wallets[4]
        forged = x.sign(_op_bytes("upload", w.address, 0, b"\2" * 32 +
                                  __import__("struct").pack("<qd", 50, 1.0)))
        assert led.upload_local_update(w.address, b"\2" * 32, 50, 1.0, 0,
                                       forged) == LedgerStatus.BAD_ARG

    def test_full_authenticated_round(self, auth_led):
        led, keys = auth_led
        for i in range(CFG.client_num):
            led.register_node(addr(i), sign_register(keys, addr(i)))
        for i in (2, 3, 4):
            h = bytes([i]) * 32
            st = led.upload_local_update(
                addr(i), h, 100 + i, 1.0, 0,
                sign_upload(keys, addr(i), h, 100 + i, 1.0, 0))
            assert st == LedgerStatus.OK
        rng = np.random.default_rng(0)
        for c in led.committee():
            scores = [float(s) for s in rng.random(3)]
            st = led.upload_scores(c, 0, scores,
                                   sign_scores(keys, c, 0, scores))
            assert st == LedgerStatus.OK
        assert led.aggregate_ready()
        # coordinator-side ops pass through unauthenticated (writer authority)
        assert led.commit_model(b"\x09" * 32,
                                0) == LedgerStatus.OK
        assert led.epoch == 1
        assert led.verify_log()


class TestPure25519Backend:
    """The from-first-principles Ed25519/X25519 fallback (comm.pure25519)
    must BE the RFC algorithms — pinned against the published test vectors
    — and byte-compatible with the `cryptography` backend wherever both
    exist, so wallets interoperate across hosts."""

    def test_ed25519_rfc8032_vectors(self):
        from bflc_demo_tpu.comm import pure25519 as p
        sk = bytes.fromhex("9d61b19deffd5a60ba844af492ec2cc4"
                           "4449c5697b326919703bac031cae7f60")
        pk = bytes.fromhex("d75a980182b10ab7d54bfed3c964073a"
                           "0ee172f3daa62325af021a68f707511a")
        sig = bytes.fromhex(
            "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e0652249"
            "01555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe2465514143"
            "8e7a100b")
        assert p.ed25519_public(sk) == pk
        assert p.ed25519_sign(sk, b"") == sig
        assert p.ed25519_verify(pk, b"", sig)
        assert not p.ed25519_verify(pk, b"x", sig)
        sk2 = bytes.fromhex("4ccd089b28ff96da9db6c346ec114e0f"
                            "5b8a319f35aba624da8cf6ed4fb8a6fb")
        pk2 = bytes.fromhex("3d4017c3e843895a92b70aa74d1b7ebc"
                            "9c982ccf2ec4968cc0cd55f12af4660c")
        sig2 = bytes.fromhex(
            "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb"
            "69da085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d2916"
            "12bb0c00")
        assert p.ed25519_public(sk2) == pk2
        assert p.ed25519_sign(sk2, b"\x72") == sig2
        # malformed inputs are False, never exceptions
        assert not p.ed25519_verify(b"\xff" * 32, b"", sig)
        assert not p.ed25519_verify(pk, b"", b"\x00" * 64)
        assert not p.ed25519_verify(pk, b"", sig[:-1])

    def test_ed25519_rfc8032_vectors_3_and_sha_abc(self):
        """The remaining short RFC 8032 §7.1 vectors (TEST 3, TEST
        SHA(abc)) — together with TEST 1/2 above they pin key expansion,
        nonce derivation and the sign equation against published
        ground truth, so the PR-3 precompute tables can never silently
        change outputs."""
        import hashlib
        from bflc_demo_tpu.comm import pure25519 as p
        sk3 = bytes.fromhex("c5aa8df43f9f837bedb7442f31dcb7b1"
                            "66d38535076f094b85ce3a2e0b4458f7")
        pk3 = bytes.fromhex("fc51cd8e6218a1a38da47ed00230f058"
                            "0816ed13ba3303ac5deb911548908025")
        msg3 = bytes.fromhex("af82")
        sig3 = bytes.fromhex(
            "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db"
            "5ac3ac18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027"
            "beceea1ec40a")
        assert p.ed25519_public(sk3) == pk3
        assert p.ed25519_sign(sk3, msg3) == sig3
        assert p.ed25519_verify(pk3, msg3, sig3)
        sk4 = bytes.fromhex("833fe62409237b9d62ec77587520911e"
                            "9a759cec1d19755b7da901b96dca3d42")
        pk4 = bytes.fromhex("ec172b93ad5e563bf4932c70e1245034"
                            "c35467ef2efd4d64ebf819683467e2bf")
        msg4 = hashlib.sha512(b"abc").digest()
        sig4 = bytes.fromhex(
            "dc2a4459e7369633a52b1bf277839a00201009a3efbf3ecb69bea2186c"
            "26b58909351fc9ac90b3ecfdfbc7c66431e0303dca179c138ac17ad9be"
            "f1177331a704")
        assert p.ed25519_public(sk4) == pk4
        assert p.ed25519_sign(sk4, msg4) == sig4
        assert p.ed25519_verify(pk4, msg4, sig4)

    def test_precompute_paths_match_naive_on_random_inputs(self):
        """PR-3 correctness guard: the windowed fixed-base table, the
        multiscalar (batch-verify) path and the caches must be
        BYTE-IDENTICAL to the naive double-and-add ladder — checked on
        randomized scalars, seeds and messages so a table-construction
        bug cannot hide behind the fixed RFC vectors."""
        import hashlib
        import random
        from bflc_demo_tpu.comm import pure25519 as p
        rng = random.Random(0xED25519)
        # scalar-mult table vs ladder on random scalars (incl. edges)
        for s in [0, 1, 2, p._L - 1, p._L, (1 << 255) - 19] + [
                rng.getrandbits(256) for _ in range(40)]:
            assert p._compress(p._pt_mul_base(s)) == \
                p._compress(p._pt_mul(s, p._G)), s
        # dedicated doubling and wNAF variable-base mul vs the ladder,
        # on arbitrary (non-base) points
        for i in range(12):
            k = rng.getrandbits(255)
            pt = p._pt_mul(k | 1, p._G)
            assert p._compress(p._pt_dbl(pt)) == \
                p._compress(p._pt_add(pt, pt))
            s = rng.getrandbits(253)
            assert p._compress(p._pt_mul_wnaf(s, pt)) == \
                p._compress(p._pt_mul(s, pt)), (k, s)
        assert p._compress(p._pt_mul_wnaf(0, p._G)) == \
            p._compress(p._pt_mul(0, p._G))
        # sign/verify: cached fast path vs from-scratch recomputation
        for i in range(10):
            seed = hashlib.sha256(b"xcheck-%d" % i).digest()
            msg = bytes(rng.getrandbits(8) for _ in range(rng.randint(
                0, 200)))
            a, prefix = p._expand_seed(seed)
            pub_naive = p._compress(p._pt_mul(a, p._G))
            assert p.ed25519_public(seed) == pub_naive
            r = int.from_bytes(hashlib.sha512(prefix + msg).digest(),
                               "little") % p._L
            r_enc = p._compress(p._pt_mul(r, p._G))
            h = int.from_bytes(hashlib.sha512(
                r_enc + pub_naive + msg).digest(), "little") % p._L
            sig_naive = r_enc + int.to_bytes((r + h * a) % p._L, 32,
                                             "little")
            assert p.ed25519_sign(seed, msg) == sig_naive
            assert p.ed25519_verify(pub_naive, msg, sig_naive)

    def test_batch_verification_agrees_with_individual(self):
        """ed25519_verify_batch: all-honest batches always pass (the
        accept direction involves no randomness); one bad signature
        anywhere fails the batch, and callers' per-item fallback then
        attributes it — so batch-then-fallback equals individual
        verification on every input."""
        import random
        from bflc_demo_tpu.comm import pure25519 as p
        rng = random.Random(7)
        seeds = [bytes([i]) * 32 for i in range(4)]
        pubs = [p.ed25519_public(s) for s in seeds]
        items = []
        for i in range(24):
            k = i % 4
            msg = bytes(rng.getrandbits(8) for _ in range(32))
            items.append((pubs[k], msg, p.ed25519_sign(seeds[k], msg)))
        assert p.ed25519_verify_batch(items)
        assert p.ed25519_verify_batch([])
        assert p.ed25519_verify_batch(items[:1])
        # one forged message → batch False, individual pinpoints it
        bad = list(items)
        bad[7] = (bad[7][0], b"forged message", bad[7][2])
        assert not p.ed25519_verify_batch(bad)
        flags = [p.ed25519_verify(pub, m, s) for pub, m, s in bad]
        assert flags.count(False) == 1 and not flags[7]
        # malformed inputs are False, never exceptions
        assert not p.ed25519_verify_batch([(b"\xff" * 32, b"m",
                                            items[0][2])])
        assert not p.ed25519_verify_batch([(pubs[0], b"m", b"\x00" * 63)])

    def test_batch_verification_is_deterministic_on_torsion_defects(self):
        """The batch equation is cofactored ON PURPOSE: a signature whose
        only defect is a small-torsion component in R must get the SAME
        verdict on every call (here: accepted, as RFC 8032 §8.9
        cofactored verification allows), never a per-call coin flip — a
        randomized verdict would let the same commit certificate count a
        quorum on one node and miss it on another.  Per-item
        (cofactorless) verification stays strictly stricter and rejects
        it deterministically too."""
        import hashlib
        from bflc_demo_tpu.comm import pure25519 as p
        seed = b"\x42" * 32
        pub = p.ed25519_public(seed)
        a, prefix = p._expand_seed(seed)
        msg = b"torsion-defect determinism"
        r = int.from_bytes(hashlib.sha512(prefix + msg).digest(),
                           "little") % p._L
        # R' = R + T2 where T2 = (0, -1) has order 2: 8*T2 = identity,
        # so the cofactored equation holds while the exact one fails
        t2 = (0, (-1) % p._P, 1, 0)
        r_enc = p._compress(p._pt_add(p._pt_mul(r, p._G), t2))
        h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(),
                           "little") % p._L
        sig = r_enc + int.to_bytes((r + h * a) % p._L, 32, "little")
        for _ in range(12):             # no coin flips either way
            assert not p.ed25519_verify(pub, msg, sig)
            assert p.ed25519_verify_batch([(pub, msg, sig)])
        # mixed with honest signatures: still deterministic
        honest = [(pub, b"h%d" % i, p.ed25519_sign(seed, b"h%d" % i))
                  for i in range(3)]
        for _ in range(6):
            assert p.ed25519_verify_batch(honest + [(pub, msg, sig)])

    def test_x25519_rfc7748_vector_and_dh_symmetry(self):
        from bflc_demo_tpu.comm import pure25519 as p
        k = bytes.fromhex("a546e36bf0527c9d3b16154b82465edd"
                          "62144c0ac1fc5a18506a2244ba449ac4")
        u = bytes.fromhex("e6db6867583030db3594c1a424b15f7c"
                          "726624ec26b3353b10a903a6d0ab1c4c")
        out = bytes.fromhex("c3da55379de9c6908e94ea4df28d084f"
                            "32eccf03491c71f754b4075577a28552")
        assert p.x25519_exchange(k, u) == out
        import hashlib
        a = hashlib.sha256(b"dh-a").digest()
        b = hashlib.sha256(b"dh-b").digest()
        assert p.x25519_exchange(a, p.x25519_public(b)) == \
            p.x25519_exchange(b, p.x25519_public(a))

    def test_backends_interoperate_when_both_exist(self):
        from bflc_demo_tpu.comm import identity as idm
        from bflc_demo_tpu.comm import pure25519 as p
        w = Wallet.from_seed(b"xbackend-1")
        msg = b"cross-backend message"
        sig = w.sign(msg)
        # the pure backend verifies whatever the active backend signed
        assert p.ed25519_verify(w.public_bytes, msg, sig)
        # and the chokepoint agrees with it
        assert idm.verify_signature(w.public_bytes, msg, sig)
        if idm.ED25519_BACKEND == "cryptography":
            # same seed -> same keys/sigs under both implementations
            assert p.ed25519_public(w._sign_sk) == w.public_bytes
            assert p.ed25519_sign(w._sign_sk, msg) == sig
            assert p.x25519_public(w._dh_sk) == w.dh_public_bytes
