"""Model-quality health plane (bflc_demo_tpu.obs.health; ISSUE 12):
the batched per-delta stats kernel, the streaming anomaly detector's
verdict semantics, the end-to-end anomaly drill (a scripted sign-flip/
scale-attack client at config-1 geometry is flagged CRIT within k
rounds, zero false CRITs on the honest leg, committed model hashes
byte-identical with the plane armed vs BFLC_HEALTH_LEGACY=1), and the
health_report post-mortem tool."""

import hashlib
import json
import os
import sys

import numpy as np
import pytest

from bflc_demo_tpu.meshagg.stats import (batch_delta_stats,
                                         weighted_mean_row)
from bflc_demo_tpu.obs import health as obs_health
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs.health import HealthMonitor, summarize_records
from bflc_demo_tpu.protocol.constants import DEFAULT_PROTOCOL
from bflc_demo_tpu.utils.serialization import pack_pytree


@pytest.fixture
def enabled_registry():
    saved_enabled = obs_metrics.REGISTRY.enabled
    saved_role = obs_metrics.REGISTRY.role
    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "writer"
    try:
        yield obs_metrics.REGISTRY
    finally:
        obs_metrics.REGISTRY.enabled = saved_enabled
        obs_metrics.REGISTRY.role = saved_role


class TestBatchDeltaStats:
    def test_stats_match_hand_computation(self):
        mat = np.array([[3.0, 4.0, 0.0],
                        [0.0, 0.0, 0.0],
                        [1.0, np.nan, np.inf]], np.float32)
        ref = np.array([3.0, 4.0, 0.0], np.float32)
        s = batch_delta_stats(mat, ref)
        assert s["l2"][0] == pytest.approx(5.0)
        assert s["max_abs"][0] == pytest.approx(4.0)
        assert s["zero_frac"][0] == pytest.approx(1 / 3)
        assert s["nonfinite"][0] == 0
        assert s["cos_ref"][0] == pytest.approx(1.0)
        # all-zero row: zero norm, cosine pinned to 0 (not NaN)
        assert s["l2"][1] == 0.0 and s["cos_ref"][1] == 0.0
        assert s["zero_frac"][1] == 1.0
        # nonfinite entries counted and excluded from the norms
        assert s["nonfinite"][2] == 2
        assert s["l2"][2] == pytest.approx(1.0)

    def test_sign_flip_reads_negative_cosine(self):
        rng = np.random.default_rng(3)
        ref = rng.standard_normal(64).astype(np.float32)
        mat = np.stack([ref, -ref])
        s = batch_delta_stats(mat, ref)
        assert s["cos_ref"][0] == pytest.approx(1.0)
        assert s["cos_ref"][1] == pytest.approx(-1.0)

    def test_no_ref_and_empty_edges(self):
        s = batch_delta_stats(np.ones((2, 4), np.float32), None)
        assert list(s["cos_ref"]) == [0.0, 0.0]
        s0 = batch_delta_stats(np.zeros((0, 0), np.float32))
        assert len(s0["l2"]) == 0

    def test_jit_leg_matches_numpy(self, monkeypatch):
        """The compiled stats leg is observability-only (no byte
        contract) but must agree with numpy to float32 tolerance."""
        from bflc_demo_tpu.meshagg import stats as mstats
        rng = np.random.default_rng(11)
        mat = rng.standard_normal((24, 50)).astype(np.float32)
        mat[3, 7] = np.nan
        mat[5, :10] = 0.0
        ref = rng.standard_normal(50).astype(np.float32)
        host = mstats._host_stats(mat, ref)
        monkeypatch.setenv("BFLC_HEALTH_STATS_JIT", "1")
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "1")
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        jit = batch_delta_stats(mat, ref)
        if mstats._JIT_BROKEN:      # platform without jax: numpy ran
            pytest.skip("jit stats leg unavailable on this platform")
        for k in host:
            np.testing.assert_allclose(jit[k], host[k], rtol=2e-5,
                                       atol=2e-5, err_msg=k)

    def test_weighted_mean_row_is_selected_weighted_mean(self):
        mat = np.array([[1.0, 0.0], [0.0, 1.0], [10.0, 10.0]],
                       np.float32)
        row = weighted_mean_row(mat, [1.0, 3.0, 99.0], [0, 1])
        np.testing.assert_allclose(row, [0.25, 0.75])


def _honest_round(rng, base, n=10, dim=16):
    return [(base + 0.3 * rng.standard_normal(dim)).astype(np.float32)
            for _ in range(n)]


class TestHealthMonitorDetector:
    def test_honest_fleet_never_flags(self):
        rng = np.random.default_rng(0)
        base = rng.standard_normal(16).astype(np.float32)
        hm = HealthMonitor(jsonl_path="")
        for ep in range(10):
            rec = hm.on_round(
                epoch=ep, senders=[f"c{i}" for i in range(10)],
                rows=_honest_round(rng, base),
                weights=[10.0] * 10, selected=list(range(6)))
            assert rec["verdict"] == "ok", rec
        assert hm.report()["flagged_senders"] == []

    def test_scale_attack_crit_within_two_rounds(self):
        rng = np.random.default_rng(1)
        base = rng.standard_normal(16).astype(np.float32)
        hm = HealthMonitor(jsonl_path="")
        verdicts = {}
        for ep in range(7):
            rows = _honest_round(rng, base)
            if ep >= 3:
                rows[4] = rows[4] * np.float32(40.0)
            rec = hm.on_round(
                epoch=ep, senders=[f"c{i}" for i in range(10)],
                rows=rows, weights=[10.0] * 10,
                selected=list(range(6)))
            verdicts[ep] = {s["sender"]: s["level"]
                            for s in rec["senders"]}
        # crit within crit_streak=2 rounds of attack start; only c4
        assert verdicts[4]["c4"] == "crit"
        assert all(lv == "ok" for ep in verdicts
                   for s, lv in verdicts[ep].items() if s != "c4")

    def test_sign_flip_crit_and_nonfinite_instant(self):
        rng = np.random.default_rng(2)
        base = rng.standard_normal(16).astype(np.float32)
        hm = HealthMonitor(jsonl_path="")
        for ep in range(5):
            rows = _honest_round(rng, base)
            if ep >= 2:
                rows[7] = -rows[7]
            rec = hm.on_round(
                epoch=ep, senders=[f"c{i}" for i in range(10)],
                rows=rows, weights=[10.0] * 10,
                selected=list(range(6)))
        by = {s["sender"]: s for s in rec["senders"]}
        assert by["c7"]["level"] == "crit"
        assert "cos_flip" in by["c7"]["reasons"]
        # NaN is CRIT on sight — no streak, no baseline needed
        hm2 = HealthMonitor(jsonl_path="")
        rows = _honest_round(rng, base, n=4)
        rows[1][0] = np.nan
        rec = hm2.on_round(epoch=0, senders=list("abcd"), rows=rows,
                           weights=[1.0] * 4, selected=[0, 1])
        assert rec["verdict"] == "crit"
        assert rec["senders"][1]["reasons"] == ["nonfinite"]

    def test_stale_streak_expires_two_isolated_outliers_never_crit(self):
        """Review regression: the crit streak must EXPIRE after
        streak_gap rounds without a trip — two isolated one-round
        outliers far apart are two WARNs, never a CRIT page."""
        rng = np.random.default_rng(5)
        base = rng.standard_normal(16).astype(np.float32)
        hm = HealthMonitor(jsonl_path="", streak_gap=8)
        verdicts = []
        for ep in range(25):
            rows = _honest_round(rng, base)
            if ep in (4, 20):           # isolated glitches, 16 apart
                rows[2] = rows[2] * np.float32(40.0)
            rec = hm.on_round(
                epoch=ep, senders=[f"c{i}" for i in range(10)],
                rows=rows, weights=[10.0] * 10,
                selected=list(range(6)))
            verdicts.append(rec["verdict"])
        assert verdicts.count("warn") == 2
        assert "crit" not in verdicts
        # ...while trips WITHIN the gap still escalate across an
        # ABSENCE (async cadence: a sender is only admitted every few
        # drains — a clean appearance resets, an absence must not)
        hm2 = HealthMonitor(jsonl_path="", streak_gap=8)
        got_crit = False
        for ep in range(12):
            senders = [f"c{i}" for i in range(10)]
            rows = _honest_round(rng, base)
            if ep >= 4 and ep % 2 == 0:
                rows[2] = rows[2] * np.float32(40.0)     # trip
            elif ep >= 4:
                del senders[2], rows[2]                  # absent
            rec = hm2.on_round(
                epoch=ep, senders=senders, rows=rows,
                weights=[10.0] * len(senders),
                selected=list(range(6)))
            got_crit = got_crit or rec["verdict"] == "crit"
        assert got_crit

    def test_nonfinite_round_extends_a_streak(self):
        """Review regression: a NaN-bearing round is instant CRIT and
        must also COUNT toward the streak — an attacker interleaving
        NaN rounds must not get its l2_z escalation reset."""
        rng = np.random.default_rng(6)
        base = rng.standard_normal(16).astype(np.float32)
        hm = HealthMonitor(jsonl_path="")
        verdicts = []
        for ep in range(6):
            rows = _honest_round(rng, base)
            if ep == 3:
                rows[2] = rows[2] * np.float32(40.0)    # l2_z trip
            elif ep == 4:
                rows[2][0] = np.nan                     # NaN round
            elif ep == 5:
                rows[2] = rows[2] * np.float32(40.0)    # l2_z again
            rec = hm.on_round(
                epoch=ep, senders=[f"c{i}" for i in range(10)],
                rows=rows, weights=[10.0] * 10,
                selected=list(range(6)))
            verdicts.append(
                {s["sender"]: s["level"] for s in rec["senders"]})
        assert verdicts[4]["c2"] == "crit"      # NaN: instant
        # the ep-5 l2_z trip rides the unbroken streak -> still CRIT
        assert verdicts[5]["c2"] == "crit"

    def test_cold_start_z_needs_baseline(self):
        """A huge first-round delta must not CRIT before the rolling
        window holds min_baseline observations."""
        hm = HealthMonitor(jsonl_path="", min_baseline=16)
        rows = [np.full(8, 1e3 * (i + 1), np.float32)
                for i in range(4)]
        rec = hm.on_round(epoch=0, senders=list("abcd"), rows=rows,
                          weights=[1.0] * 4, selected=[0])
        assert rec["verdict"] == "ok"
        assert all(s["z"] is None for s in rec["senders"])

    def test_round_record_convergence_fields_and_jsonl(self, tmp_path):
        path = str(tmp_path / "w.health.jsonl")
        hm = HealthMonitor(jsonl_path=path)
        old = np.zeros(8)
        new = np.full(8, 0.1)
        rec = hm.on_round(
            epoch=5, senders=["a", "b"],
            rows=[np.ones(8, np.float32), np.ones(8, np.float32)],
            weights=[1.0, 3.0], selected=[0, 1],
            medians=[0.6, 0.4],
            candidate_scores=[[0.5, 0.7], [0.3, 0.5]],
            staleness=[0, 3], old_row=old, new_row=new, mode="async")
        assert rec["update_norm"] == pytest.approx(
            float(np.sqrt(8 * 0.01)), abs=1e-5)
        assert rec["score_median"] == pytest.approx(0.5)
        # per-candidate IQR of a 2-member row is half its range (0.1)
        assert rec["score_disagreement"] == pytest.approx(0.1)
        assert rec["staleness"] == {"min": 0, "max": 3, "mean": 1.5}
        # contribution ledger: weight shares sum to 1 over selected
        assert hm.contribution["b"]["weight_share"] == pytest.approx(
            0.75)
        # the jsonl record parses and summarizes
        lines = [json.loads(ln) for ln in open(path)]
        assert lines[0]["type"] == "health_round"
        summ = summarize_records(lines)
        assert summ["rounds"] == 1
        assert summ["verdicts"]["ok"] == 1

    def test_legacy_pin_disarms(self, monkeypatch, enabled_registry):
        monkeypatch.setenv("BFLC_HEALTH_LEGACY", "1")
        assert not obs_health.health_armed()
        monkeypatch.delenv("BFLC_HEALTH_LEGACY")
        assert obs_health.health_armed()


# ---------------------------------------------------------------- drill
def _delta_for(client: int, epoch: int, base: np.ndarray,
               dim: int) -> np.ndarray:
    """Deterministic per-(client, epoch) honest delta — both drill
    legs regenerate byte-identical uploads."""
    rng = np.random.default_rng([client, epoch, 1234])
    return (base + 0.3 * rng.standard_normal(dim)).astype(np.float32)


def _run_drill(rounds: int, attacker: str, attack_from: int):
    """Scripted config-1-geometry federation against a real
    LedgerServer dispatch surface (auth off — the drill scripts every
    role): 10 trainer uploads + 4 committee score rows per round, the
    attacker's delta sign-flipped AND scaled from `attack_from` on.
    Returns (per-round committed model hashes, server) — the caller
    closes it."""
    from bflc_demo_tpu.comm.ledger_service import LedgerServer

    cfg = DEFAULT_PROTOCOL        # 20 clients / comm 4 / top-6 / 10
    dim = 12
    rng = np.random.default_rng(99)
    base = rng.standard_normal(dim).astype(np.float32)
    blob0 = pack_pytree({"W": np.zeros(dim, np.float32)})
    server = LedgerServer(cfg, blob0, require_auth=False,
                          stall_timeout_s=3600.0)
    addrs = [f"c{i:02d}" for i in range(cfg.client_num)]
    for a in addrs:
        assert server._dispatch("register", {"addr": a})["ok"]
    hashes = []
    for _ in range(rounds):
        ep = server.ledger.epoch
        committee = server._dispatch("committee", {})["committee"]
        trainers = sorted(a for a in addrs if a not in committee)
        # attacker uploads LAST (slot 9) so the scripted scores below
        # keep it out of the rotating committee; 9 honest trainers
        # fill the other slots
        uploaders = [a for a in trainers
                     if a != attacker][:cfg.needed_update_count - 1]
        uploaders.append(attacker)
        for a in uploaders:
            d = _delta_for(addrs.index(a), ep, base, dim)
            if a == attacker and ep >= attack_from:
                d = (-20.0 * d).astype(np.float32)
            blob = pack_pytree({"W": d})
            r = server._dispatch("upload", {
                "addr": a, "blob": blob,
                "hash": hashlib.sha256(blob).hexdigest(),
                "n": 10, "cost": 1.0, "epoch": ep})
            assert r["ok"], (a, r)
        # deterministic committee outcome: earlier slots score higher,
        # so selection and the next committee are slot-ordered and the
        # attacker (slot 9) never seats
        row = [1.0 - 0.05 * j for j in range(cfg.needed_update_count)]
        for a in committee:
            r = server._dispatch("scores", {"addr": a, "epoch": ep,
                                            "scores": row})
            assert r["ok"], (a, r)
        assert server.ledger.epoch == ep + 1, "round did not commit"
        hashes.append(server._model_hash)
    return hashes, server


class TestAnomalyDrill:
    """The acceptance drill: config-1 geometry, scripted sign-flip +
    scale attacker, flagged CRIT within k rounds, zero false CRITs on
    the honest leg, certified model hashes byte-identical armed vs
    pinned off."""

    ROUNDS = 7
    ATTACK_FROM = 3
    K = 3                   # flag budget (rounds after attack start)

    def test_attacker_flagged_crit_within_k_no_false_crits(
            self, tmp_path, enabled_registry, monkeypatch):
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        obs_health.install(str(tmp_path))
        try:
            hashes, server = _run_drill(self.ROUNDS, "c19",
                                        self.ATTACK_FROM)
            assert server._health is not None
            records = list(server._health.records)
            server.close()
            assert len(records) == self.ROUNDS
            by_epoch = {r["epoch"]: r for r in records}
            # flagged CRIT within K rounds of the attack starting...
            crit_epochs = [
                e for e, r in by_epoch.items()
                if any(s["sender"] == "c19" and s["level"] == "crit"
                       for s in r["senders"])]
            assert crit_epochs, "attacker never went CRIT"
            assert min(crit_epochs) <= self.ATTACK_FROM + self.K
            # ...for the right reasons (sign-flip and/or magnitude)
            reasons = {r for e in crit_epochs for s in
                       by_epoch[e]["senders"] if s["sender"] == "c19"
                       for r in s["reasons"]}
            assert reasons & {"cos_flip", "l2_z"}
            # no honest sender ever CRITs in the attack leg either
            for r in records:
                for s in r["senders"]:
                    if s["sender"] != "c19":
                        assert s["level"] != "crit", (r["epoch"], s)
            # pre-attack rounds are green
            for e in range(self.ATTACK_FROM):
                assert by_epoch[e]["verdict"] == "ok"
            # the committee-score capture path worked end to end (the
            # ledger's read-only committee_score_rows surface): real
            # medians, zero disagreement (the drill's committee rows
            # are identical by construction)
            assert all(r["score_median"] > 0 for r in records)
            assert all(r["score_disagreement"] == 0.0
                       for r in records)
            # the verdict surfaced as metrics on the scrape plane
            snap = obs_metrics.REGISTRY.snapshot()["metrics"]
            crit_total = sum(
                s["value"] for s in
                snap["health_verdicts_total"]["samples"]
                if s["labels"].get("level") == "crit")
            assert crit_total >= 1
        finally:
            obs_health.install("")

    def test_honest_leg_zero_false_crits(self, enabled_registry,
                                         monkeypatch):
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        hashes, server = _run_drill(self.ROUNDS, attacker="c19",
                                    attack_from=10 ** 9)
        records = list(server._health.records)
        server.close()
        assert len(records) == self.ROUNDS
        assert all(r["verdict"] != "crit" for r in records)
        assert all(s["level"] != "crit"
                   for r in records for s in r["senders"])

    def test_model_hashes_byte_identical_armed_vs_legacy(
            self, enabled_registry, monkeypatch):
        """Health plane armed vs BFLC_HEALTH_LEGACY=1 over the SAME
        scripted attack: every committed model hash equal — the plane
        observes, it never touches the certified bytes."""
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        armed, s1 = _run_drill(self.ROUNDS, "c19", self.ATTACK_FROM)
        assert s1._health is not None and s1._health.rounds > 0
        s1.close()
        monkeypatch.setenv("BFLC_HEALTH_LEGACY", "1")
        legacy, s2 = _run_drill(self.ROUNDS, "c19", self.ATTACK_FROM)
        assert s2._health is None       # plane never armed
        s2.close()
        assert armed == legacy
        assert len(set(armed)) == self.ROUNDS   # model really moved


class TestCellTierHealth:
    def test_member_level_stats_at_the_cell(self, enabled_registry,
                                            monkeypatch):
        """The cell aggregator feeds its MEMBERS' deltas to its own
        monitor (mode='cell') when it seals a partial — member-level
        anomalies are caught one tier below the root."""
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        from bflc_demo_tpu.comm.identity import provision_wallets
        from bflc_demo_tpu.hier.aggregator import CellAggregatorServer
        from bflc_demo_tpu.protocol.constants import ProtocolConfig
        cfg = ProtocolConfig(client_num=6, comm_count=2,
                             aggregate_count=2, needed_update_count=3,
                             learning_rate=0.05,
                             batch_size=16).validate()
        wallets, _ = provision_wallets(1, b"cell-health-test-seed")
        blob0 = pack_pytree({"W": np.zeros(8, np.float32)})
        srv = CellAggregatorServer(
            cfg, blob0, 0, wallets[0], [("127.0.0.1", 1)],
            require_auth=False, stall_timeout_s=3600.0)
        try:
            addrs = [f"m{i}" for i in range(6)]
            for a in addrs:
                assert srv._dispatch("register", {"addr": a})["ok"]
            ep = srv.ledger.epoch
            committee = srv._dispatch("committee", {})["committee"]
            trainers = sorted(a for a in addrs
                              if a not in committee)[:3]
            rng = np.random.default_rng(0)
            for a in trainers:
                blob = pack_pytree(
                    {"W": rng.standard_normal(8).astype(np.float32)})
                r = srv._dispatch("upload", {
                    "addr": a, "blob": blob,
                    "hash": hashlib.sha256(blob).hexdigest(),
                    "n": 5, "cost": 1.0, "epoch": ep})
                assert r["ok"], r
            for a in committee:
                assert srv._dispatch(
                    "scores", {"addr": a, "epoch": ep,
                               "scores": [0.9, 0.8, 0.7]})["ok"]
            assert srv._outbox is not None      # partial sealed
            assert srv._health is not None
            rec = srv._health.records[-1]
            assert rec["mode"] == "cell" and rec["n"] == 3
            assert {s["sender"] for s in rec["senders"]} == \
                set(trainers)
        finally:
            srv.close()


class TestHealthReportTool:
    def _tool(self):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        try:
            import health_report
        finally:
            sys.path.pop(0)
        return health_report

    def test_report_over_drill_artifacts(self, tmp_path,
                                         enabled_registry,
                                         monkeypatch, capsys):
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        obs_health.install(str(tmp_path))
        try:
            _, server = _run_drill(5, "c19", 2)
            server.close()
        finally:
            obs_health.install("")
        tool = self._tool()
        records = tool.load_health_records(str(tmp_path))
        assert records and all(r["type"] == "health_round"
                               for r in records)
        out_json = str(tmp_path / "health_report_drill.json")
        assert tool.main([str(tmp_path), "--out", out_json]) == 0
        md = capsys.readouterr().out
        assert "Per-round verdicts" in md
        assert "c19" in md                     # flagged ranking names it
        summary = json.load(open(out_json))
        ranked = summary["flagged_senders"]
        assert ranked and ranked[0]["sender"] == "c19"
        # contribution ledger rebuilt offline from the records
        assert summary["contribution"]["c19"]["admitted"] == 5

    def test_empty_dir_is_a_clean_error(self, tmp_path):
        assert self._tool().main([str(tmp_path)]) == 2
