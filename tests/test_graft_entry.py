"""Driver-surface guard: `__graft_entry__` must ALWAYS work.

Round-4 post-mortem: `make_sharded_protocol_round` grew mandatory kwargs
(static comm_count / needed_update_count for the new default committee
scoring schedule); every internal call site was updated but the externally
visible driver entry point was not, so `dryrun_multichip` raised before any
compute and the round shipped zero multi-device evidence
(MULTICHIP_r04.json rc=1 — a regression from green in rounds 2-3).
Nothing in tests/ executed the entry surface, so nothing could catch it.

These tests execute the REAL driver surface — the same module, the same
functions, the same call paths the driver runs — so an API change that
breaks the contract fails CI instead of silently zeroing out the round's
evidence.  Reference behavior being evidenced downstream: the replicated
committee round of CommitteePrecompiled.cpp:349-456.
"""

import os
import sys

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    """entry() returns (fn, args) and jax.jit(fn)(*args) executes."""
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    out = jax.block_until_ready(out)
    assert out.shape == (256, 2)
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_two_devices():
    """The full multichip dryrun executes on a 2-device mesh.

    This is the exact function the driver calls (with n=8); n=2 exercises
    every geometry branch (FL round incl. committee scoring, dp x tp, ring
    attention, MoE, sp x tp, pp, 1F1B, secure aggregation) at the smallest
    mesh that has real collectives.  conftest.py pins 8 virtual CPU devices,
    so this runs in-process.
    """
    graft.dryrun_multichip(2)
