"""Ledger ops CLI: inspect/verify/head against generated WALs, including
torn-record recovery semantics."""

import hashlib
import struct

import pytest

pytest.importorskip(
    "hypothesis", reason="fuzz cases here need the hypothesis package")
from hypothesis import given, settings, strategies as st  # noqa: E402

from bflc_demo_tpu.ledger import make_ledger, LedgerStatus
from bflc_demo_tpu.ledger.tool import main, iter_wal_ops, decode_op
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=4, comm_count=2, aggregate_count=1,
                     needed_update_count=2, learning_rate=0.05, batch_size=8)
CFG_FLAGS = ["--client-num", "4", "--comm-count", "2",
             "--aggregate-count", "1", "--needed-update-count", "2",
             "--learning-rate", "0.05", "--batch-size", "8"]


@pytest.fixture
def wal(tmp_path):
    led = make_ledger(CFG, backend="python")
    path = str(tmp_path / "run.wal")
    assert led.attach_wal(path)
    for i in range(4):
        assert led.register_node(f"0x{i:040x}") == LedgerStatus.OK
    for i in (2, 3):
        h = hashlib.sha256(bytes([i])).digest()
        assert led.upload_local_update(f"0x{i:040x}", h, 10, 1.0,
                                       0) == LedgerStatus.OK
    for i in (0, 1):
        assert led.upload_scores(f"0x{i:040x}", 0,
                                 [0.9, 0.8]) == LedgerStatus.OK
    assert led.commit_model(hashlib.sha256(b"new").digest(),
                            0) == LedgerStatus.OK
    led.detach_wal()
    return path, led.log_head().hex(), led.log_size()


def test_inspect_decodes_every_record(wal, capsys):
    path, _, size = wal
    assert main(["inspect", path]) == 0
    out = capsys.readouterr().out
    assert f"{size} record(s) decoded" in out
    assert "op=register" in out and "op=commit" in out
    ops = [decode_op(op) for _, op in iter_wal_ops(path)]
    assert [o["op"] for o in ops] == (
        ["register"] * 4 + ["upload"] * 2 + ["scores"] * 2 + ["commit"])
    assert ops[4]["n_samples"] == 10 and ops[6]["scores"] == [0.9, 0.8]


@pytest.mark.parametrize("backend", ["python", "native"])
def test_verify_and_head_match_writer(wal, capsys, backend):
    path, head, size = wal
    assert main(["verify", path, "--backend", backend, "--json",
                 *CFG_FLAGS]) == 0
    out = capsys.readouterr().out
    assert f'"log_head": "{head}"' in out
    assert '"chain_verified": true' in out
    assert main(["head", path, "--backend", backend, *CFG_FLAGS]) == 0
    assert capsys.readouterr().out.strip() == head


def test_torn_tail_stops_cleanly(wal, tmp_path, capsys):
    """A torn trailing record (crash mid-write) decodes up to the tear —
    the WAL recovery contract."""
    path, _, size = wal
    blob = open(path, "rb").read()
    torn = str(tmp_path / "torn.wal")
    with open(torn, "wb") as f:
        f.write(blob + struct.pack("<Q", 10_000) + b"\x01partial")
    ops = list(iter_wal_ops(torn))
    assert len(ops) == size                 # tear excluded, prefix intact
    assert main(["verify", torn, "--json", *CFG_FLAGS]) == 0


def test_not_a_wal_raises(tmp_path):
    bad = tmp_path / "x.wal"
    bad.write_bytes(b"garbage")
    with pytest.raises(ValueError, match="not a bflc WAL"):
        list(iter_wal_ops(str(bad)))


class TestDecodeFuzz:
    """decode_op is a rendering function for untrusted bytes: it must never
    raise, only report malformed-ness."""

    @given(st.binary(max_size=200))
    @settings(max_examples=300, deadline=None)
    def test_never_raises_on_arbitrary_bytes(self, blob):
        rec = decode_op(blob)
        assert isinstance(rec, dict) and "op" in rec

    @given(st.integers(1, 7), st.binary(max_size=120))
    @settings(max_examples=300, deadline=None)
    def test_never_raises_on_valid_opcode_garbage_body(self, code, body):
        rec = decode_op(bytes([code]) + body)
        assert isinstance(rec, dict)
