"""Certified snapshots: ledger compaction, WAL GC, crash-safe state-sync.

The tentpole property set (ledger/snapshot.py + the comm wiring):

- the canonical state encoding is byte-identical across backends, and the
  snapshot op binds it into the hash chain by local RE-DERIVATION — a
  corrupt digest refuses on every honest replica, which is what makes a
  BFT quorum's co-signature an independent proof of the checkpoint;
- GC'd ledgers stay verifiable (chain heads, clone, WAL2 journal) and a
  restored replica replays only the tail;
- torn / bit-flipped / stale artifacts are REFUSED, with fallback to the
  previous retained artifact — never a half-installed checkpoint;
- a joiner whose resume point was GC'd state-syncs through the live
  serving surfaces (writer RPC, standby read fan-out) instead of
  replaying from genesis, and a forged offer cannot install;
- BFLC_SNAPSHOT_LEGACY=1 / snapshot_interval=0 pins the
  replay-from-genesis behavior: no snapshot op ever enters the chain.
"""

import hashlib
import os
import struct
import threading
import time
import warnings

import numpy as np
import pytest

from bflc_demo_tpu.ledger import (LedgerStatus, clone_prefix, make_ledger,
                                  bindings)
from bflc_demo_tpu.ledger.pyledger import PyLedger
from bflc_demo_tpu.ledger.snapshot import (OP_SNAPSHOT, decode_state,
                                           encode_state_dict,
                                           latest_snapshot,
                                           list_snapshot_files,
                                           make_snapshot_op,
                                           parse_snapshot_op,
                                           prune_snapshots,
                                           read_snapshot_file,
                                           restore_snapshot,
                                           snapshot_base_head,
                                           verify_snapshot_meta,
                                           write_snapshot_file)
from bflc_demo_tpu.protocol import ProtocolConfig
from bflc_demo_tpu.utils.serialization import pack_pytree

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)

BACKENDS = ["python"] + (["native"] if bindings.native_available() else [])

ADDRS = [f"0x{i:040x}" for i in range(CFG.client_num)]


def _fill(led):
    for a in ADDRS:
        assert led.register_node(a) == LedgerStatus.OK


def _drive_round(led):
    """One full round straight on the ledger surface (no sockets)."""
    ep = led.epoch
    committee = led.committee()
    got = 0
    for a in ADDRS:
        if a in committee:
            continue
        h = hashlib.sha256(f"{ep}|{a}".encode()).digest()
        if led.upload_local_update(a, h, 10, 1.0, ep) == LedgerStatus.OK:
            got += 1
        if got >= CFG.needed_update_count:
            break
    for a in committee:
        assert led.upload_scores(a, ep, [0.5, 0.6, 0.7]) == LedgerStatus.OK
    mh = hashlib.sha256(f"model{ep}".encode()).digest()
    assert led.commit_model(mh, ep) == LedgerStatus.OK


def _ledger_with_rounds(n=2, backend="python"):
    led = make_ledger(CFG, backend=backend)
    _fill(led)
    for _ in range(n):
        _drive_round(led)
    return led


def _snapshot_meta(led, model=b"model-blob-bytes"):
    """Emit a snapshot op on `led` and return its offer meta (the shape
    verify_snapshot_meta/write_snapshot_file take)."""
    pos = led.log_size()
    prev = led.log_head()
    state = led.encode_state()
    op = make_snapshot_op(led)
    assert led.apply_op(op) == LedgerStatus.OK
    d = decode_state(state)
    if model is not None and bytes(d["model_hash"]) != b"\0" * 32:
        # make the fake model blob hash-consistent by patching the meta
        # consumer side: tests that need a REAL model pass one through
        pass
    return {"i": pos, "epoch": led.epoch, "gen": led.generation,
            "op": op, "prev_head": prev, "cert": None, "state": state,
            "model": model}


class TestCanonicalState:
    def test_roundtrip(self):
        led = _ledger_with_rounds(1)
        state = led.encode_state()
        d = decode_state(state)
        assert encode_state_dict(d) == state
        assert d["epoch"] == led.epoch
        assert d["reg_order"] == ADDRS

    @pytest.mark.skipif("native" not in BACKENDS,
                        reason="native ledger not built")
    def test_backends_agree_byte_for_byte(self):
        """The differential bar: same history -> same canonical bytes ->
        same state digest on BOTH backends, at several protocol phases
        (registration, mid-round with pending scores, post-commit)."""
        nat, py = make_ledger(CFG, backend="native"), \
            make_ledger(CFG, backend="python")
        for led in (nat, py):
            _fill(led)
        assert nat.encode_state() == py.encode_state()
        for led in (nat, py):
            _drive_round(led)
        assert nat.encode_state() == py.encode_state()
        assert nat.state_digest() == py.state_digest()

    def test_truncated_and_trailing_refuse(self):
        state = _ledger_with_rounds(1).encode_state()
        with pytest.raises(ValueError):
            decode_state(state[: len(state) // 2])
        with pytest.raises(ValueError):
            decode_state(state + b"\0")
        with pytest.raises(ValueError):
            decode_state(b"not-a-state-blob")


class TestSnapshotOp:
    @pytest.mark.parametrize("backend", BACKENDS)
    def test_apply_rederives_digest(self, backend):
        led = _ledger_with_rounds(1, backend=backend)
        op = make_snapshot_op(led)
        size = led.log_size()
        assert led.apply_op(op) == LedgerStatus.OK
        assert led.log_size() == size + 1
        ep, digest = parse_snapshot_op(op)
        assert ep == led.epoch and digest == led.state_digest()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_lying_digest_refused(self, backend):
        """A writer cannot bind a snapshot whose digest its replicas do
        not re-derive — THE property that makes quorum co-signature an
        independent proof of the checkpoint."""
        led = _ledger_with_rounds(1, backend=backend)
        op = bytearray(make_snapshot_op(led))
        op[-1] ^= 0xFF                          # corrupt state digest
        assert led.apply_op(bytes(op)) == LedgerStatus.BAD_ARG
        op = bytearray(make_snapshot_op(led))
        struct.pack_into("<q", op, 1, led.epoch + 3)   # wrong epoch
        assert led.apply_op(bytes(op)) == LedgerStatus.BAD_ARG

    def test_backends_chain_identically(self):
        if "native" not in BACKENDS:
            pytest.skip("native ledger not built")
        nat, py = make_ledger(CFG, backend="native"), \
            make_ledger(CFG, backend="python")
        for led in (nat, py):
            _fill(led)
            _drive_round(led)
            assert led.apply_op(make_snapshot_op(led)) == LedgerStatus.OK
        assert nat.log_head() == py.log_head()

    def test_parse_rejects_garbage(self):
        assert parse_snapshot_op(b"") is None
        assert parse_snapshot_op(b"\x04" + b"\0" * 40) is None
        assert parse_snapshot_op(bytes([OP_SNAPSHOT]) + b"\0" * 39) is None


class TestGcAndRestore:
    def test_gc_prefix_keeps_chain_verifiable(self):
        led = _ledger_with_rounds(2)
        meta = _snapshot_meta(led)
        pos = meta["i"]
        head = led.log_head()
        size = led.log_size()
        dropped = led.gc_prefix(pos + 1, meta["state"])
        assert dropped == pos + 1
        assert led.log_base == pos + 1
        assert led.log_size() == size          # positions are absolute
        assert led.log_head() == head
        assert led.verify_log()
        with pytest.raises(IndexError):
            led.log_op(0)                      # the prefix is GONE
        with pytest.raises(ValueError):
            led.head_at(pos)                   # heads below base too
        # the protocol keeps running on the compacted ledger
        _drive_round(led)
        assert led.verify_log()

    def test_restored_replica_replays_only_the_tail(self):
        led = _ledger_with_rounds(2)
        meta = _snapshot_meta(led)
        _drive_round(led)                      # the tail
        rep = restore_snapshot(meta["state"], CFG, meta["i"] + 1,
                               snapshot_base_head(meta))
        assert rep.log_size() == meta["i"] + 1
        for j in range(meta["i"] + 1, led.log_size()):
            assert rep.apply_op(led.log_op(j)) == LedgerStatus.OK
        assert rep.log_head() == led.log_head()
        assert rep.state_digest() == led.state_digest()

    def test_clone_prefix_on_compacted_ledger(self):
        led = _ledger_with_rounds(2)
        meta = _snapshot_meta(led)
        led.gc_prefix(meta["i"] + 1, meta["state"])
        _drive_round(led)
        cl = clone_prefix(led, led.log_size(), CFG)
        assert cl.log_head() == led.log_head()
        # below the base there is nothing to clone onto: certified
        # history is never rolled back past a certified snapshot
        with pytest.raises(RuntimeError):
            clone_prefix(led, meta["i"], CFG)

    def test_compacted_wal_roundtrips(self, tmp_path):
        wal = str(tmp_path / "led.wal")
        led = make_ledger(CFG, backend="python")
        assert led.attach_wal(wal)
        _fill(led)
        for _ in range(2):
            _drive_round(led)
        full_bytes = os.path.getsize(wal)
        meta = _snapshot_meta(led)
        led.gc_prefix(meta["i"] + 1, meta["state"])   # compacts the WAL
        _drive_round(led)
        led.detach_wal()
        assert os.path.getsize(wal) < full_bytes
        fresh = PyLedger(CFG.client_num, CFG.comm_count,
                         CFG.aggregate_count, CFG.needed_update_count,
                         CFG.genesis_epoch)
        fresh.replay_wal(wal)
        assert fresh.log_head() == led.log_head()
        assert fresh.log_size() == led.log_size()
        assert fresh.log_base == led.log_base
        assert fresh.state_digest() == led.state_digest()

    def test_wal_bytes_bounded_across_rounds(self, tmp_path):
        """The unbounded-growth axis, closed: with GC every round the
        journal's byte size plateaus instead of growing linearly."""
        wal = str(tmp_path / "bounded.wal")
        led = make_ledger(CFG, backend="python")
        assert led.attach_wal(wal)
        _fill(led)
        sizes = []
        for _ in range(8):
            _drive_round(led)
            state = led.encode_state()
            assert led.apply_op(make_snapshot_op(led)) == LedgerStatus.OK
            led.gc_prefix(led.log_size(), None)
            sizes.append(os.path.getsize(wal))
        # after the first GC the journal holds ONE round + snapshot
        # header: flat within a few hundred bytes, not linear in rounds
        assert max(sizes[2:]) - min(sizes[2:]) < 512, sizes
        led.detach_wal()


class TestArtifacts:
    def _meta(self):
        led = _ledger_with_rounds(1)
        return _snapshot_meta(led)

    def test_roundtrip(self, tmp_path):
        meta = self._meta()
        p = write_snapshot_file(str(tmp_path), meta)
        m = read_snapshot_file(p)
        assert bytes(m["state"]) == bytes(meta["state"])
        assert bytes(m["model"]) == bytes(meta["model"])
        assert m["i"] == meta["i"] and m["epoch"] == meta["epoch"]

    @pytest.mark.parametrize("corruption", ["truncate", "bitflip-blob",
                                            "bitflip-header"])
    def test_torn_and_corrupt_refuse_and_fall_back(self, tmp_path,
                                                   corruption):
        """Installer contract: a bad newest artifact is refused and the
        PREVIOUS retained snapshot serves instead — never a
        half-install, never a dead directory."""
        d = str(tmp_path)
        led = _ledger_with_rounds(1)
        good = _snapshot_meta(led)
        write_snapshot_file(d, good)
        _drive_round(led)
        newer = _snapshot_meta(led)
        p = write_snapshot_file(d, newer)
        blob = bytearray(open(p, "rb").read())
        if corruption == "truncate":
            blob = blob[: len(blob) - 9]       # SIGKILL mid-write shape
        elif corruption == "bitflip-blob":
            blob[-3] ^= 0x40                   # disk rot in the model
        else:
            blob[3] ^= 0x01                    # disk rot in the magic
        with open(p, "wb") as fh:
            fh.write(bytes(blob))
        with pytest.raises(ValueError):
            read_snapshot_file(p)
        fb = latest_snapshot(d)
        assert fb is not None and fb["i"] == good["i"]

    def test_prune_retention(self, tmp_path):
        d = str(tmp_path)
        led = _ledger_with_rounds(1)
        for _ in range(4):
            write_snapshot_file(d, _snapshot_meta(led))
            _drive_round(led)
        assert len(list_snapshot_files(d)) == 4
        assert prune_snapshots(d, keep=2) == 2
        assert len(list_snapshot_files(d)) == 2


class TestVerifyMeta:
    """The joiner's trust gate, attacked piecewise."""

    def _bft_fixture(self):
        from bflc_demo_tpu.comm.bft import (CertificateAssembler,
                                            ValidatorNode,
                                            provision_validators)
        from bflc_demo_tpu.protocol import bft_quorum
        vwallets, vkeys = provision_validators(4, b"snapmeta-v-01")
        nodes = [ValidatorNode(CFG, w, i, validator_keys=vkeys,
                               require_auth=False)
                 for i, w in enumerate(vwallets)]
        for v in nodes:
            v.start()
        return nodes, vkeys, bft_quorum(4), CertificateAssembler

    def test_hash_checks(self):
        led = _ledger_with_rounds(1)
        meta = _snapshot_meta(led, model=None)
        assert verify_snapshot_meta(meta) == ""
        bad = dict(meta, state=bytes(meta["state"])[:-1] + b"\xee")
        assert "digest" in verify_snapshot_meta(bad)
        bad = dict(meta, model=b"not the committed model")
        assert "model" in verify_snapshot_meta(bad)
        assert "malformed" in verify_snapshot_meta({"i": "x"})

    def test_generation_regression_refused(self):
        led = _ledger_with_rounds(1)
        meta = _snapshot_meta(led, model=None)
        assert "backwards" in verify_snapshot_meta(meta,
                                                   min_generation=5)

    def test_stale_or_forged_certificate_refused(self):
        """With validator keys provisioned the offer MUST chain-link:
        no cert, a cert for a different position, and a tampered cert
        all refuse; the honest quorum cert passes."""
        nodes, vkeys, quorum, Assembler = self._bft_fixture()
        try:
            led = _ledger_with_rounds(0)       # registration ops only
            asm = Assembler([(v.host, v.port) for v in nodes], vkeys,
                            quorum,
                            backlog_fn=lambda j: (led.log_op(j), None))
            # certify the whole backlog, then the snapshot op
            prev = b"\0" * 32
            from bflc_demo_tpu.comm.bft import next_head
            for j in range(led.log_size()):
                cert = asm.certify(j, led.log_op(j), None, prev)
                assert cert is not None, f"op {j} failed certification"
                prev = next_head(prev, led.log_op(j))
            meta = _snapshot_meta(led, model=None)
            cert = asm.certify(meta["i"], meta["op"], None,
                               meta["prev_head"])
            assert cert is not None, "quorum refused an honest snapshot"
            meta["cert"] = cert.to_wire()
            asm.close()
            ok = verify_snapshot_meta(meta, bft_quorum=quorum,
                                      bft_keys=vkeys)
            assert ok == "", ok
            assert "certificate" in verify_snapshot_meta(
                dict(meta, cert=None), bft_quorum=quorum, bft_keys=vkeys)
            stale = dict(meta, i=meta["i"] + 7)
            assert "quorum-bind" in verify_snapshot_meta(
                stale, bft_quorum=quorum, bft_keys=vkeys)
            tampered = dict(meta["cert"], t=9)
            assert "quorum-bind" in verify_snapshot_meta(
                dict(meta, cert=tampered), bft_quorum=quorum,
                bft_keys=vkeys)
        finally:
            for v in nodes:
                v.close()


# --------------------------------------------------------- live serving
def _init_blob():
    return pack_pytree({"W": np.zeros((5, 2), np.float32),
                        "b": np.zeros((2,), np.float32)})


def _drive_socket_round(c, addrs):
    ep = c.request("info")["epoch"]
    committee = c.request("committee")["committee"]
    got = 0
    for i, a in enumerate(a for a in addrs if a not in committee):
        blob = pack_pytree({"W": np.full((5, 2), i + ep + 1.0,
                                         np.float32),
                            "b": np.zeros((2,), np.float32)})
        digest = hashlib.sha256(blob).digest()
        if c.request("upload", addr=a, blob=blob.hex(),
                     hash=digest.hex(), n=10, cost=1.0,
                     epoch=ep).get("ok"):
            got += 1
        if got >= CFG.needed_update_count:
            break
    for a in committee:
        assert c.request("scores", addr=a, epoch=ep,
                         scores=[0.5, 0.55, 0.6])["ok"]


def _await(cond, timeout_s=15.0, step=0.2):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


class TestLiveStateSync:
    """The serving surfaces, one shared fleet: a writer emitting + GC'ing
    certified snapshots, a fresh standby that must STATE-SYNC (its
    resume point is GC'd), streamed-snapshot mirroring + standby GC, the
    read fan-out serving the mirrored checkpoint, and `replicate`'s
    snapshot path."""

    def test_writer_gc_standby_state_sync_and_fanout(self, tmp_path):
        from bflc_demo_tpu.comm.failover import Standby
        from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                       LedgerServer,
                                                       replicate)
        snapdir = str(tmp_path / "snaps")
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=2.0, ledger_backend="python",
                           snapshot_interval=2, snapshot_dir=snapdir)
        srv.start()
        sb = None
        c = CoordinatorClient(srv.host, srv.port)
        try:
            for a in ADDRS:
                assert c.request("register", addr=a)["ok"]
            for _ in range(4):
                _drive_socket_round(c, ADDRS)
            assert _await(lambda: c.request("info").get("log_base", 0)
                          > 0), "writer never GC'd"
            info = c.request("info")
            # GC is observable end to end: prefix reads answer
            # PREFIX_GC, the artifact landed tmp-then-rename
            r = c.request("log_range", start=0, end=4)
            assert r.get("error") == "PREFIX_GC" and r["base"] > 0
            assert list_snapshot_files(snapdir)
            assert not any(n.endswith(".tmp")
                           for n in os.listdir(snapdir))

            # fresh standby: resume point 0 is gone -> state-sync + tail
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")    # wallet-less standby
                sb = Standby(CFG, [(srv.host, srv.port),
                                   ("127.0.0.1", 0)], 1,
                             stall_timeout_s=2.0, snapshot_interval=2)
            sb.endpoints[1] = (sb.host, sb.port)
            threading.Thread(target=sb.run, daemon=True).start()
            assert _await(lambda: sb.ledger.log_size()
                          >= info["log_size"]), "standby never synced"
            assert sb.ledger.log_base > 0, \
                "standby replayed from genesis instead of state-syncing"
            assert sb.ledger.log_head() == bytes.fromhex(
                c.request("info")["log_head"])
            assert sb._model_blob is not None

            # two more rounds stream a NEW snapshot op: the standby must
            # mirror it, GC its own replica, and serve it on the fan-out
            base0 = sb.ledger.log_base
            for _ in range(2):
                _drive_socket_round(c, ADDRS)
            assert _await(lambda: sb.ledger.log_base > base0), \
                "standby never GC'd behind the streamed snapshot"
            assert sb._latest_snapshot is not None
            rc = CoordinatorClient(*sb.read_server.endpoint)
            try:
                r = rc.request("snapshot")
                assert r["ok"] and r["i"] == sb._latest_snapshot["i"]
                # the replica declines a request for a DIFFERENT
                # checkpoint in one tiny frame (the `want_i` probe)
                r2 = rc.request("snapshot", want_i=r["i"] + 1)
                assert not r2["ok"] and r2.get("status") == "STALE"
            finally:
                rc.close()

            # replicate() takes the same snapshot path
            info = c.request("info")
            rep = replicate(srv.host, srv.port, CFG,
                            until_ops=info["log_size"], timeout_s=30.0,
                            ledger_backend="python")
            assert rep.log_head().hex() == c.request("info")["log_head"] \
                or rep.log_size() >= info["log_size"]
        finally:
            if sb is not None:
                sb.stop()
            c.close()
            srv.close()

    def test_forged_offer_never_installs(self):
        """A Byzantine writer hands a fresh standby a corrupt snapshot:
        the standby must REFUSE (loud RuntimeError) and install
        nothing."""
        from bflc_demo_tpu.comm.failover import Standby
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        srv = _LyingSnapshotServer()
        srv.start()
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sb = Standby(CFG, [(srv.host, srv.port),
                                   ("127.0.0.1", 0)], 1,
                             stall_timeout_s=2.0, snapshot_interval=2)
            ctl = CoordinatorClient(srv.host, srv.port)
            try:
                with pytest.raises(RuntimeError, match="refusing"):
                    sb._state_sync(ctl)
                assert sb.ledger.log_size() == 0       # nothing installed
                assert sb._model_blob is None
            finally:
                ctl.close()
                sb.stop()
        finally:
            srv.close()

    def test_legacy_pins_snapshots_off(self, monkeypatch):
        """BFLC_SNAPSHOT_LEGACY=1 (and snapshot_interval=0) keep the
        chain byte-for-byte snapshot-free: no opcode-9 op, no GC."""
        from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                       LedgerServer)
        heads = {}
        for mode in ("legacy", "interval0"):
            if mode == "legacy":
                monkeypatch.setenv("BFLC_SNAPSHOT_LEGACY", "1")
                interval = 2
            else:
                monkeypatch.delenv("BFLC_SNAPSHOT_LEGACY",
                                   raising=False)
                interval = 0
            srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                               stall_timeout_s=2.0,
                               ledger_backend="python",
                               snapshot_interval=interval)
            srv.start()
            c = CoordinatorClient(srv.host, srv.port)
            try:
                for a in ADDRS:
                    assert c.request("register", addr=a)["ok"]
                for _ in range(2):
                    _drive_socket_round(c, ADDRS)
                time.sleep(1.2)            # monitor loop had its chance
                info = c.request("info")
                assert info.get("log_base", 0) == 0
                assert "snapshot_epoch" not in info
                ops = c.request("log_range", start=0,
                                end=info["log_size"])["ops"]
                assert all(bytes.fromhex(o)[0] != OP_SNAPSHOT
                           for o in ops)
                heads[mode] = info["log_head"]
            finally:
                c.close()
                srv.close()
        # both pins produce the identical chain
        assert heads["legacy"] == heads["interval0"]


class _LyingSnapshotServer:
    """Minimal writer impostor: answers info with a GC'd base and serves
    a snapshot whose state bytes do not hash to the op's digest."""

    def __init__(self):
        import socket as _socket
        led = make_ledger(CFG, backend="python")
        _fill(led)
        _drive_round(led)
        self._meta = _snapshot_meta(led, model=b"m")
        self._state = bytes(self._meta["state"])
        self._sock = _socket.socket()
        self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _loop(self):
        from bflc_demo_tpu.comm.wire import recv_msg, send_msg
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                while True:
                    m = recv_msg(conn)
                    if m is None:
                        break
                    if m.get("method") == "info":
                        send_msg(conn, {"ok": True, "epoch": 1, "gen": 0,
                                        "log_size": self._meta["i"] + 1,
                                        "log_head": "00" * 32,
                                        "log_base": self._meta["i"] + 1})
                    elif m.get("method") == "snapshot":
                        corrupt = bytearray(self._state)
                        corrupt[-1] ^= 0xFF
                        send_msg(conn, {
                            "ok": True, "i": self._meta["i"],
                            "epoch": self._meta["epoch"], "gen": 0,
                            "op": self._meta["op"].hex(),
                            "prev_head": self._meta["prev_head"].hex(),
                            "cert": None, "state": bytes(corrupt),
                            "model": b"m"})
                    else:
                        send_msg(conn, {"ok": False, "error": "nope"})
            except Exception:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass


class TestChaosDrill:
    """The acceptance drill at fleet scope: a BFT writer emitting
    quorum-certified snapshots, a standby OS process SIGKILLed
    mid-follow, the writer GC'ing the log/WAL prefix PAST the dead
    replica's resume point, and the restarted standby catching up —
    which can only happen via state-sync, because the ops below the GC
    base no longer exist to replay.  The chaos `InvariantMonitor` runs
    across the whole drill (it must adopt the certified snapshot as its
    replay base — an unverifiable offer after GC is itself a
    violation).  The refusal half of the acceptance pair is
    `TestLiveStateSync::test_forged_offer_never_installs`."""

    def _model_epoch_served(self, eps):
        """Highest model epoch any advertised read-fan-out endpoint
        serves (-1 when none answer)."""
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        best = -1
        for host, port in eps or []:
            try:
                rc = CoordinatorClient(host, port)
                try:
                    r = rc.request("model", meta=1)
                finally:
                    rc.close()
            except (ConnectionError, OSError):
                continue
            if r.get("ok"):
                best = max(best, int(r.get("epoch", -1)))
        return best

    def test_sigkill_standby_gc_rejoin_state_sync(self, tmp_path):
        import dataclasses
        import multiprocessing as mp
        import signal

        from bflc_demo_tpu.chaos.invariants import InvariantMonitor
        from bflc_demo_tpu.client.process_runtime import _standby_proc
        from bflc_demo_tpu.comm.bft import (ValidatorNode,
                                            provision_validators)
        from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                       LedgerServer)

        from bflc_demo_tpu.comm.identity import Wallet

        snapdir = str(tmp_path / "snaps")
        wal = str(tmp_path / "writer.wal")
        sb_seed = b"snapdrill-standby-1"
        sb_keys = {1: Wallet.from_seed(sb_seed).public_bytes}
        vwallets, vkeys = provision_validators(4, b"snapdrill-v-01")
        nodes = [ValidatorNode(CFG, w, i, validator_keys=vkeys,
                               require_auth=False)
                 for i, w in enumerate(vwallets)]
        for v in nodes:
            v.start()
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=2.0, ledger_backend="python",
                           wal_path=wal,
                           bft_validators=[(v.host, v.port)
                                           for v in nodes],
                           bft_keys=vkeys,
                           standby_keys=sb_keys,
                           snapshot_interval=2, snapshot_dir=snapdir)
        srv.start()
        monitor = InvariantMonitor([(v.host, v.port) for v in nodes],
                                   bft_enabled=True)
        ctx = mp.get_context("spawn")
        cfg_kw = dataclasses.asdict(CFG)
        proc = None

        def _spawn_standby(port):
            q = ctx.Queue()
            p = ctx.Process(target=_standby_proc,
                            args=(cfg_kw, [(srv.host, srv.port)], 1, q,
                                  2.0, "", sb_seed, sb_keys,
                                  0, [(v.host, v.port) for v in nodes],
                                  vkeys, False, port, None, None,
                                  2, ""),
                            daemon=True)
            p.start()
            return p, q.get(timeout=60)

        c = CoordinatorClient(srv.host, srv.port)
        try:
            for a in ADDRS:
                assert c.request("register", addr=a)["ok"]
            proc, sbport = _spawn_standby(0)
            _drive_socket_round(c, ADDRS)
            # the standby is following: its advertised read fan-out
            # serves the round-1 model
            assert _await(lambda: self._model_epoch_served(
                c.request("model", meta=1).get("read_set")) >= 1,
                timeout_s=30.0), "standby never followed"
            info = c.request("info")
            monitor.observe_info(info)
            resume_point = info["log_size"]     # the dead standby's
            #                                     best-possible resume

            os.kill(proc.pid, signal.SIGKILL)   # the drill's hammer
            proc.join(timeout=10)

            # writer keeps going: snapshots certify, GC advances PAST
            # the dead replica's resume point (the dead subscription
            # must not hold the prefix hostage)
            for _ in range(4):
                _drive_socket_round(c, ADDRS)
                info = c.request("info")
                monitor.observe_info(info)
            assert _await(lambda: c.request("info").get("log_base", 0)
                          > resume_point, timeout_s=30.0), \
                "writer never GC'd past the dead standby's resume point"
            monitor.check_history(c, c.request("info"))
            # the monitor crossed the GC'd prefix via the certified
            # snapshot, not by pretending it read it
            assert monitor.checks.get("snapshot_bases_installed", 0) >= 1

            # restart on the same port: resume point 0 is GC'd, so the
            # ONLY path back is snapshot + tail
            proc, sbport2 = _spawn_standby(sbport)
            assert sbport2 == sbport
            want = c.request("info")["epoch"]
            assert _await(lambda: self._model_epoch_served(
                c.request("model", meta=1).get("read_set")) >= want,
                timeout_s=45.0), \
                "restarted standby never state-synced to the tip"

            # settle, then strict final verdicts over the GC'd chain
            assert _await(lambda: (lambda i: i.get("certified_size")
                                   == i["log_size"])(c.request("info")),
                          timeout_s=30.0), "certification never settled"
            info = c.request("info")
            verdicts = monitor.final_check(c, info, [])
            assert monitor.violations == [], monitor.violations
            assert verdicts["monotone_progress"] == "PASS"
            assert verdicts["no_uncertified_bind"] == "PASS"
            assert verdicts["single_certified_history"] == "PASS", \
                verdicts
        finally:
            if proc is not None and proc.is_alive():
                proc.terminate()
                proc.join(timeout=10)
            c.close()
            srv.close()
            for v in nodes:
                v.close()
