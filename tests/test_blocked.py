"""REDUCTION SPEC v2 — protocol-agreed blocked reduction (ISSUE 18).

Three properties carry the whole feature:

- **byte invariance**: partitioning the flattened param axis into any
  number of contiguous blocks (genome field ``reduce_blocks``) changes
  NOTHING about the committed bytes — per-element accumulation order is
  untouched, blocks only concatenate — pinned against the ISSUE-11
  golden digests and the scripted end-to-end committed model hashes;
- **device-count independence**: the blocked mesh leg reproduces the
  blocked host reference (and therefore the v1 bytes) on 1, 2, 4 and 8
  forced host devices — the partition comes from the genome, never from
  ``jax.device_count()``;
- **geometry is certified**: commit ops carry the block-count claim
  (``BLK1`` tail), and a writer claiming a geometry that disagrees with
  the replica's genome dies with BAD_ARG before any state mutates — the
  lying-writer drill.
"""

import hashlib
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
from bflc_demo_tpu.ledger.base import reduce_blocks
from bflc_demo_tpu.ledger.pyledger import _BLOCKS_MAGIC, PyLedger
from bflc_demo_tpu.meshagg import spec
from bflc_demo_tpu.meshagg.engine import ENGINE
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils.serialization import pack_entries

from test_meshagg import (GOLDEN_AGG, GOLDEN_ASYNC_MODEL, GOLDEN_CELL,
                          GOLDEN_SYNC_MODEL, _async_drain_model_hash,
                          _golden_scenario, _sync_round_model_hash)


class TestBlockBounds:
    """spec.block_bounds is the NORMATIVE partition — every consumer
    (engine legs, host reference, rederive) derives from it."""

    def test_partition_covers_contiguously(self):
        for p in (1, 5, 42, 97, 4096):
            for blocks in (1, 2, 3, 7, p):
                if blocks > p:
                    continue
                bounds = spec.block_bounds(p, blocks)
                assert bounds[0][0] == 0 and bounds[-1][1] == p
                for (_, hi), (lo2, _) in zip(bounds, bounds[1:]):
                    assert hi == lo2
                pb = -(-p // blocks)
                assert all(hi - lo == pb for lo, hi in bounds[:-1])
                assert 0 < bounds[-1][1] - bounds[-1][0] <= pb

    def test_empty_model_is_one_empty_block(self):
        assert spec.block_bounds(0, 1) == [(0, 0)]
        assert spec.block_bounds(0, 1)[0][1] - \
            spec.block_bounds(0, 1)[0][0] == 0

    def test_degenerate_geometry_rejected(self):
        with pytest.raises(ValueError, match="degenerate"):
            spec.block_bounds(42, 43)
        with pytest.raises(ValueError):
            spec.block_bounds(10, 0)
        with pytest.raises(ValueError):
            spec.block_bounds(10, -1)

    def test_genome_validation(self):
        assert ProtocolConfig(reduce_blocks=1).validate()
        assert ProtocolConfig(reduce_blocks=2).validate()
        assert ProtocolConfig(reduce_blocks=65536).validate()
        with pytest.raises(ValueError, match="reduce_blocks"):
            ProtocolConfig(reduce_blocks=0).validate()
        with pytest.raises(ValueError, match="reduce_blocks"):
            ProtocolConfig(reduce_blocks=-3).validate()
        with pytest.raises(ValueError, match="degenerate"):
            ProtocolConfig(reduce_blocks=65537).validate()

    def test_legacy_env_pins_v1(self, monkeypatch):
        cfg = ProtocolConfig(reduce_blocks=8)
        assert reduce_blocks(cfg) == 8
        monkeypatch.setenv("BFLC_BLOCKED_LEGACY", "1")
        assert reduce_blocks(cfg) == 1


class TestBlockedGoldenPins:
    """Any block count reproduces the ISSUE-11 golden digests — the
    certified arithmetic is invariant under the v2 execution shape."""

    @pytest.mark.parametrize("blocks", [2, 8])
    @pytest.mark.parametrize("leg", ["host", "mesh"])
    def test_blocked_merge_pins_golden_bytes(self, blocks, leg):
        _, _, _, g, deltas, weights, selected = _golden_scenario()
        out = ENGINE.aggregate_flat(g, deltas, weights, selected, 0.05,
                                    force_leg=leg, blocks=blocks)
        assert hashlib.sha256(
            pack_entries(out)).hexdigest() == GOLDEN_AGG
        assert ENGINE.last_blocks == blocks

    def test_blocked_leg_accounting(self):
        _, _, _, g, deltas, weights, selected = _golden_scenario()
        before = ENGINE.calls.get("blocked", 0)
        ENGINE.aggregate_flat(g, deltas, weights, selected, 0.05,
                              force_leg="blocked")
        assert ENGINE.calls.get("blocked", 0) == before + 1
        assert ENGINE.last_leg == "blocked"

    def test_blocked_host_reference_equals_v1_host(self):
        _, keys, _, _, deltas, weights, selected = _golden_scenario()
        keys = sorted(keys)
        w = spec.merge_weight_vector(weights, selected, len(deltas))
        wsum = max(float(w.sum()), 1e-12)
        v1 = spec.host_weighted_sum(keys, deltas, w, wsum)
        p = sum(int(np.asarray(deltas[0][k]).size) for k in keys)
        for blocks in (1, 2, 5, 8, 64, p):
            v2 = spec.blocked_host_weighted_sum(keys, deltas, w, wsum,
                                                blocks)
            for k in keys:
                assert np.asarray(v2[k]).tobytes() == \
                    np.asarray(v1[k]).tobytes(), (blocks, k)

    def test_cell_partial_blocked_pins_golden_bytes(self):
        from bflc_demo_tpu.hier.partial import cell_partial
        rng, keys, shapes, _, _, _, _ = _golden_scenario()
        admitted = []
        for i in range(7):
            flat = {k: rng.standard_normal(shapes[k]).astype(np.float32)
                    for k in keys}
            admitted.append((f"0x{i:040x}", flat, 10 + 3 * i,
                             0.5 + 0.1 * i))
        partial, n, _ = cell_partial(admitted, blocks=3)
        assert hashlib.sha256(
            pack_entries(partial)).hexdigest() == GOLDEN_CELL
        assert n == 7

    def test_shard_rederive_clamps_small_subsets(self):
        """A rederive shard restricted to a key subset smaller than the
        genome's block count must clamp, not raise — and the bytes are
        invariant either way."""
        from bflc_demo_tpu.rederive.core import derive_leaves
        _, keys, _, g, deltas, weights, selected = _golden_scenario()
        sub = [sorted(keys)[1]]                     # one (8,) leaf: P=8
        flats = [d if i in set(selected) else None
                 for i, d in enumerate(deltas)]
        v1 = derive_leaves(g, flats, weights, selected, 0.05, sub)
        vb = derive_leaves(g, flats, weights, selected, 0.05, sub,
                           blocks=4096)
        assert np.asarray(vb[sub[0]]).tobytes() == \
            np.asarray(v1[sub[0]]).tobytes()


class TestBlockedCertifiedHashParity:
    """The scripted end-to-end rounds, re-run under a blocked genome:
    the COMMITTED MODEL HASHES must equal the v1 goldens bit-for-bit
    (reduce_blocks is an execution-shape knob, not an arithmetic one),
    and BFLC_BLOCKED_LEGACY=1 must pin the v1 wire too."""

    def test_sync_round_blocked_genome_pins_golden(self, monkeypatch):
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "1")
        assert _sync_round_model_hash(
            reduce_blocks=2) == GOLDEN_SYNC_MODEL

    def test_sync_round_legacy_env_pins_v1_wire(self, monkeypatch):
        monkeypatch.setenv("BFLC_BLOCKED_LEGACY", "1")
        assert _sync_round_model_hash(
            reduce_blocks=2) == GOLDEN_SYNC_MODEL

    def test_async_drain_blocked_genome_pins_golden(self, monkeypatch):
        monkeypatch.delenv("BFLC_MESH_AGG_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_MESH_AGG_MIN", "1")
        assert _async_drain_model_hash(
            reduce_blocks=2) == GOLDEN_ASYNC_MODEL


def _addr(i):
    return f"0x{i:040x}"


def _drive_round(led, cfg, epoch=0):
    for i in range(cfg.comm_count, cfg.client_num):
        led.upload_local_update(
            _addr(i), hashlib.sha256(f"p{i}@{epoch}".encode()).digest(),
            300 + i, 1.5, epoch)
    rng = np.random.default_rng(42 + epoch)
    for c in led.committee():
        led.upload_scores(c, epoch, list(rng.random(
            cfg.needed_update_count).astype(np.float32)))


class TestGeometryClaimWire:
    """The lying-writer drill: the block-count claim rides the commit
    op; any disagreement with the replica's genome is BAD_ARG before
    state mutates — so every BFT validator's re-execution refuses to
    co-sign a writer lying about its reduction geometry."""

    CFG2 = ProtocolConfig(reduce_blocks=2)
    CFG1 = ProtocolConfig()

    def _committed_writer(self, cfg):
        led = make_ledger(cfg)
        for i in range(cfg.client_num):
            led.register_node(_addr(i))
        _drive_round(led, cfg)
        st = led.commit_model(hashlib.sha256(b"m1").digest(), 0)
        assert st == LedgerStatus.OK
        return led

    def _replay_prefix(self, cfg, src, upto):
        led = make_ledger(cfg, backend="python")
        for j in range(upto):
            assert led.apply_op(src.log_op(j)) == LedgerStatus.OK, j
        return led

    def test_blocked_genome_needs_python_backend(self):
        with pytest.raises(ValueError, match="geometry-claim"):
            make_ledger(self.CFG2, backend="native")
        assert isinstance(make_ledger(self.CFG2), PyLedger)

    def test_commit_op_carries_blk1_tail(self):
        w = self._committed_writer(self.CFG2)
        body = w.log_op(w.log_size() - 1)[1:]
        assert len(body) == 52
        assert body[40:44] == _BLOCKS_MAGIC
        assert struct.unpack("<q", body[44:])[0] == 2

    def test_v1_commit_op_bytes_unchanged(self):
        w = self._committed_writer(self.CFG1)
        assert len(w.log_op(w.log_size() - 1)[1:]) == 40

    def test_honest_blocked_chain_replays(self):
        w = self._committed_writer(self.CFG2)
        r = self._replay_prefix(self.CFG2, w, w.log_size())
        assert r.log_head() == w.log_head()

    def test_v1_replica_refuses_blocked_claim(self):
        w = self._committed_writer(self.CFG2)
        r = self._replay_prefix(self.CFG1, w, w.log_size() - 1)
        op = w.log_op(w.log_size() - 1)
        assert r.apply_op(op) == LedgerStatus.BAD_ARG

    def test_blocked_replica_refuses_plain_v1_commit(self):
        w = self._committed_writer(self.CFG1)
        r = self._replay_prefix(self.CFG2, w, w.log_size() - 1)
        assert r.apply_op(
            w.log_op(w.log_size() - 1)) == LedgerStatus.BAD_ARG

    def test_lying_geometry_claim_dies_before_state(self):
        w = self._committed_writer(self.CFG2)
        op = w.log_op(w.log_size() - 1)
        lie = bytes([op[0]]) + op[1:41] + _BLOCKS_MAGIC + \
            struct.pack("<q", 8)
        r = self._replay_prefix(self.CFG2, w, w.log_size() - 1)
        head, epoch = r.log_head(), r.epoch
        # validate_op (the BFT probe) refuses and restores
        assert r.validate_op(lie) == LedgerStatus.BAD_ARG
        assert r.log_head() == head and r.epoch == epoch
        # apply_op refuses without mutating
        assert r.apply_op(lie) == LedgerStatus.BAD_ARG
        assert r.log_head() == head and r.epoch == epoch
        # garbage tails are BAD_ARG, not silently ignored
        assert r.apply_op(bytes([op[0]]) + op[1:41]
                          + b"XY") == LedgerStatus.BAD_ARG
        # the honest op still lands afterwards
        assert r.apply_op(op) == LedgerStatus.OK
        assert r.log_head() == w.log_head()

    def test_async_drain_claim_wire(self):
        cfg2 = ProtocolConfig(async_buffer=8, reduce_blocks=2)
        cfg1 = ProtocolConfig(async_buffer=8)

        def seeded(cfg):
            led = make_ledger(cfg)
            for i in range(cfg.client_num):
                led.register_node(_addr(i))
            return led

        w = seeded(cfg2)
        for i in range(4, 8):
            assert w.async_upload(
                _addr(i), hashlib.sha256(f"a{i}".encode()).digest(),
                100 + i, 1.0, 0) == LedgerStatus.OK
        assert w.async_commit(hashlib.sha256(b"am").digest(), 0,
                              3) == LedgerStatus.OK
        aop = w.log_op(w.log_size() - 1)
        body = aop[1:]
        assert body[48:52] == _BLOCKS_MAGIC
        assert struct.unpack("<q", body[52:])[0] == 2
        # honest replay
        r = make_ledger(cfg2)
        for j in range(w.log_size()):
            assert r.apply_op(w.log_op(j)) == LedgerStatus.OK, j
        assert r.log_head() == w.log_head()
        # v1-async replica refuses the tagged drain
        r1 = make_ledger(cfg1)
        for j in range(w.log_size() - 1):
            assert r1.apply_op(w.log_op(j)) == LedgerStatus.OK
        assert r1.apply_op(aop) == LedgerStatus.BAD_ARG
        # lying claim and stripped tail both refused by blocked replica
        r2 = make_ledger(cfg2)
        for j in range(w.log_size() - 1):
            assert r2.apply_op(w.log_op(j)) == LedgerStatus.OK
        lie = bytes([aop[0]]) + body[:48] + _BLOCKS_MAGIC + \
            struct.pack("<q", 16)
        assert r2.apply_op(lie) == LedgerStatus.BAD_ARG
        assert r2.apply_op(
            bytes([aop[0]]) + body[:48]) == LedgerStatus.BAD_ARG
        assert r2.apply_op(aop) == LedgerStatus.OK


class TestDeviceCountIndependence:
    """The partition is genome, not hardware: conftest forces 8 host
    devices, and blocks=8 divides 8, so the sharded params-axis cube
    program actually runs here — its bytes must equal the blocked host
    reference and the v1 host loop."""

    def test_sharded_cube_leg_matches_host_bytes(self):
        import jax
        assert jax.device_count() == 8, jax.devices()
        rng = np.random.default_rng(20260807)
        keys = ["/W", "/b"]
        deltas = [{"/W": rng.standard_normal((10, 4)).astype(np.float32),
                   "/b": rng.standard_normal((8,)).astype(np.float32)}
                  for _ in range(24)]
        w = spec.merge_weight_vector(
            [float(5 + i) for i in range(24)], list(range(24)), 24)
        wsum = max(float(w.sum()), 1e-12)
        v1 = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                 force_leg="host")
        blocked = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                      force_leg="mesh", blocks=8)
        for k in keys:
            assert np.asarray(blocked[k]).tobytes() == \
                np.asarray(v1[k]).tobytes(), k
        assert ENGINE.last_blocks == 8
        # the padded-cube program was compiled for this geometry
        assert any(sig[0] == "blk" for sig in ENGINE._programs
                   if isinstance(sig, tuple))

    @pytest.mark.slow
    @pytest.mark.parametrize("ndev", [1, 2, 4])
    def test_forced_device_counts_reproduce_bytes(self, ndev):
        """Subprocess with a forced N-device CPU backend: the blocked
        mesh leg's certified hash is a constant — computed fresh per
        device count and compared against the in-process 8-device
        value via the blocked HOST reference (pure numpy, device-free,
        identical everywhere by construction)."""
        code = (
            "import hashlib\n"
            "import numpy as np\n"
            "import jax\n"
            "assert jax.device_count() == %d, jax.devices()\n"
            "from bflc_demo_tpu.meshagg import spec\n"
            "from bflc_demo_tpu.meshagg.engine import ENGINE\n"
            "from bflc_demo_tpu.utils.serialization import pack_entries\n"
            "rng = np.random.default_rng(20260807)\n"
            "keys = ['/W', '/b']\n"
            "deltas = [{'/W': rng.standard_normal((10, 4))"
            ".astype(np.float32),\n"
            "           '/b': rng.standard_normal((8,))"
            ".astype(np.float32)}\n"
            "          for _ in range(24)]\n"
            "w = spec.merge_weight_vector([float(5 + i) "
            "for i in range(24)], list(range(24)), 24)\n"
            "wsum = max(float(w.sum()), 1e-12)\n"
            "m = ENGINE.weighted_sum(keys, deltas, w, wsum, "
            "force_leg='mesh', blocks=8)\n"
            "h = spec.blocked_host_weighted_sum(keys, deltas, w, "
            "wsum, 8)\n"
            "for k in keys:\n"
            "    assert np.asarray(m[k]).tobytes() == "
            "np.asarray(h[k]).tobytes(), k\n"
            "print('DEVHASH', hashlib.sha256(pack_entries("
            "{k: np.asarray(m[k]) for k in keys})).hexdigest())\n"
        ) % ndev
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=("--xla_force_host_platform_device_count"
                              f"={ndev}"))
        r = subprocess.run([sys.executable, "-c", code],
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))),
                           capture_output=True, text=True, timeout=300,
                           env=env)
        assert r.returncode == 0, r.stderr[-2000:]
        got = [ln for ln in r.stdout.splitlines()
               if ln.startswith("DEVHASH")][0].split()[1]
        # the same scenario through the device-free host reference in
        # THIS process — equality across processes = across counts
        rng = np.random.default_rng(20260807)
        keys = ["/W", "/b"]
        deltas = [{"/W": rng.standard_normal((10, 4)).astype(np.float32),
                   "/b": rng.standard_normal((8,)).astype(np.float32)}
                  for _ in range(24)]
        w = spec.merge_weight_vector(
            [float(5 + i) for i in range(24)], list(range(24)), 24)
        ref = spec.blocked_host_weighted_sum(
            keys, deltas, w, max(float(w.sum()), 1e-12), 8)
        want = hashlib.sha256(pack_entries(
            {k: np.asarray(ref[k]) for k in keys})).hexdigest()
        assert got == want, (ndev, got, want)
