"""Ledger tests: protocol guards (reference .cpp:215-297), deterministic
election, hash-chained log, and native<->python differential equivalence."""

import hashlib

import numpy as np
import pytest

from bflc_demo_tpu.ledger import (make_ledger, LedgerStatus, PyLedger)
from bflc_demo_tpu.ledger import bindings
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig()  # reference genome

BACKENDS = ["python"] + (["native"] if bindings.native_available() else [])


def addr(i):
    return f"0x{i:040x}"


def fill_registration(led, n=None):
    for i in range(n or CFG.client_num):
        assert led.register_node(addr(i)) == LedgerStatus.OK


def run_upload_phase(led, epoch=0, n=None):
    """Uploads from the 16 trainers; first 10 accepted."""
    statuses = []
    for i in range(CFG.comm_count, CFG.client_num):
        h = hashlib.sha256(f"payload{i}@{epoch}".encode()).digest()
        statuses.append(led.upload_local_update(addr(i), h, 300 + i,
                                                1.5 + i * 0.1, epoch))
        if n and sum(s == LedgerStatus.OK for s in statuses) >= n:
            break
    return statuses


@pytest.fixture(params=BACKENDS)
def led(request):
    return make_ledger(CFG, backend=request.param)


class TestRegistration:
    def test_genesis_state(self, led):
        assert led.epoch == CFG.genesis_epoch
        model, ep = led.query_global_model()
        assert model == b"\0" * 32 and ep == CFG.genesis_epoch

    def test_start_trigger_and_committee(self, led):
        """CLIENT_NUM registrations -> epoch 0, first 4 registrants comm
        (.cpp:175-186; ordered-registration determinism is our spec)."""
        fill_registration(led, CFG.client_num - 1)
        assert led.epoch == CFG.genesis_epoch
        led.register_node(addr(CFG.client_num - 1))
        assert led.epoch == 0
        assert led.committee() == [addr(i) for i in range(4)]
        assert led.query_state(addr(0))[0] == "comm"
        assert led.query_state(addr(7))[0] == "trainer"

    def test_unknown_address_reads_trainer(self, led):
        """QueryState defaults to trainer, unpersisted (.cpp:191-205)."""
        role, _ = led.query_state("0xdeadbeef")
        assert role == "trainer"
        assert led.num_registered == 0

    def test_duplicate_registration(self, led):
        led.register_node(addr(1))
        assert led.register_node(addr(1)) == LedgerStatus.ALREADY_REGISTERED
        assert led.num_registered == 1


class TestUploadGuards:
    def test_before_start(self, led):
        st = led.upload_local_update(addr(5), b"\1" * 32, 100, 1.0, 0)
        assert st == LedgerStatus.NOT_STARTED

    def test_wrong_epoch(self, led):
        fill_registration(led)
        st = led.upload_local_update(addr(5), b"\1" * 32, 100, 1.0, 7)
        assert st == LedgerStatus.WRONG_EPOCH  # .cpp:225-226

    def test_duplicate_and_cap(self, led):
        fill_registration(led)
        run_upload_phase(led)
        # dup (.cpp:232-233)
        st = led.upload_local_update(addr(4), b"\1" * 32, 100, 1.0, 0)
        assert st == LedgerStatus.DUPLICATE
        # cap: 10 accepted, the rest rejected (.cpp:239-244)
        assert led.update_count == CFG.needed_update_count
        st = led.upload_local_update(addr(19), b"\1" * 32, 100, 1.0, 0)
        assert st == LedgerStatus.CAP_REACHED

    def test_query_all_updates_gate(self, led):
        """Empty until update_count >= NEEDED_UPDATE_COUNT (.cpp:304-311)."""
        fill_registration(led)
        run_upload_phase(led, n=9)
        if led.update_count < 10:
            assert led.query_all_updates() == []


class TestScoringAndRound:
    def _full_round(self, led, epoch=0):
        run_upload_phase(led, epoch=epoch)
        ups = led.query_all_updates()
        assert len(ups) == 10
        rng = np.random.default_rng(42 + epoch)
        for c in led.committee():
            scores = rng.random(10).astype(np.float32)
            assert led.upload_scores(c, epoch, list(scores)) == LedgerStatus.OK
        return ups

    def test_score_guards(self, led):
        fill_registration(led)
        run_upload_phase(led)
        # non-committee scorer (.cpp:272-275)
        st = led.upload_scores(addr(10), 0, [0.5] * 10)
        assert st == LedgerStatus.NOT_COMMITTEE
        # wrong epoch (.cpp:266-269)
        st = led.upload_scores(addr(0), 3, [0.5] * 10)
        assert st == LedgerStatus.WRONG_EPOCH
        # wrong length
        st = led.upload_scores(addr(0), 0, [0.5] * 7)
        assert st == LedgerStatus.BAD_ARG

    def test_rescore_does_not_double_count(self, led):
        """Spec'd divergence from the unconditional ++ at .cpp:285-289."""
        fill_registration(led)
        run_upload_phase(led)
        led.upload_scores(addr(0), 0, [0.5] * 10)
        led.upload_scores(addr(0), 0, [0.6] * 10)
        assert led.score_count == 1
        assert not led.aggregate_ready()

    def test_pending_frozen_against_rescore(self, led):
        """A late re-score after the committee completes must not mutate the
        selection the compute plane is applying (reviewed race)."""
        fill_registration(led)
        self._full_round(led)
        assert led.aggregate_ready()
        before = led.pending()
        scorer = led.committee()[0]
        st = led.upload_scores(scorer, 0, [0.99] * 10)
        assert st == LedgerStatus.NOT_READY
        after = led.pending()
        assert after.order == before.order
        assert abs(after.global_loss - before.global_loss) < 1e-9

    def test_aggregation_pipeline(self, led):
        fill_registration(led)
        ups = self._full_round(led)
        assert led.aggregate_ready()
        p = led.pending()
        assert len(p.order) == 10 and len(p.selected) == 6
        # loss = mean avg_cost of selected (.cpp:416-425)
        expect = np.float32(np.mean([np.float32(ups[s].avg_cost)
                                     for s in p.selected]))
        assert abs(p.global_loss - expect) < 1e-5
        # commit: epoch advances, committee re-elected from top-4 slots
        new_comm_expect = {ups[s].sender for s in p.order[:4]}
        assert led.commit_model(b"\2" * 32, 0) == LedgerStatus.OK
        assert led.epoch == 1
        assert set(led.committee()) == new_comm_expect
        assert led.update_count == 0 and led.score_count == 0
        model, ep = led.query_global_model()
        assert model == b"\2" * 32 and ep == 1

    def test_commit_guards(self, led):
        fill_registration(led)
        assert led.commit_model(b"\2" * 32, 0) == LedgerStatus.NOT_READY
        self._full_round(led)
        assert led.commit_model(b"\2" * 32, 5) == LedgerStatus.WRONG_EPOCH

    def test_multi_round_epochs_monotonic(self, led):
        fill_registration(led)
        for ep in range(3):
            self._full_round(led, epoch=ep)
            assert led.commit_model(bytes([ep + 1] * 32), ep) == LedgerStatus.OK
            assert led.epoch == ep + 1


class TestLog:
    def test_chain_verifies_and_rejects_tamper(self):
        led = make_ledger(CFG, backend="python")
        fill_registration(led)
        run_upload_phase(led)
        assert led.verify_log()
        led._log[3] = b"\7" * 32   # tamper
        assert not led.verify_log()

    def test_rejected_ops_not_logged(self, led):
        fill_registration(led)
        size = led.log_size()
        led.upload_local_update(addr(5), b"\1" * 32, 100, 1.0, 99)  # rejected
        assert led.log_size() == size

    def test_replay_reaches_same_head(self, led):
        fill_registration(led)
        run_upload_phase(led)
        for c in led.committee():
            led.upload_scores(c, 0, [0.5] * 10)
        led.commit_model(b"\3" * 32, 0)
        replica = make_ledger(CFG, backend="python")
        for i in range(led.log_size()):
            assert replica.apply_op(led.log_op(i)) == LedgerStatus.OK
        assert replica.log_head() == led.log_head()
        assert replica.epoch == led.epoch
        assert replica.committee() == led.committee()


@pytest.mark.skipif(not bindings.native_available(),
                    reason="native ledger not built")
class TestNativePythonEquivalence:
    """The C++ ledger and the Python mirror must be indistinguishable."""

    def test_sha256_matches_hashlib(self):
        for payload in [b"", b"abc", b"x" * 1000, bytes(range(256)) * 5]:
            assert (bindings.sha256_native(payload)
                    == hashlib.sha256(payload).digest())

    def test_full_session_identical(self):
        nat = make_ledger(CFG, backend="native")
        py = make_ledger(CFG, backend="python")
        rng = np.random.default_rng(7)
        for led in (nat, py):
            fill_registration(led)
        for ep in range(3):
            scores_by_round = rng.random((4, 10)).astype(np.float32)
            for led in (nat, py):
                sts = run_upload_phase(led, epoch=ep)
                comm = led.committee()
                for ci, c in enumerate(comm):
                    led.upload_scores(c, ep, list(scores_by_round[ci]))
                led.commit_model(bytes([ep] * 32), ep)
            assert nat.epoch == py.epoch
            assert nat.committee() == py.committee()
            assert abs(nat.last_global_loss - py.last_global_loss) < 1e-6
            assert nat.log_head() == py.log_head(), f"log diverged at ep {ep}"
        assert nat.verify_log() and py.verify_log()

    def test_cross_replay(self):
        """Ops recorded by the native ledger replay into a Python replica."""
        nat = make_ledger(CFG, backend="native")
        fill_registration(nat)
        run_upload_phase(nat)
        for c in nat.committee():
            nat.upload_scores(c, 0, [0.25] * 10)
        nat.commit_model(b"\x09" * 32, 0)
        py = make_ledger(CFG, backend="python")
        for i in range(nat.log_size()):
            assert py.apply_op(nat.log_op(i)) == LedgerStatus.OK
        assert py.log_head() == nat.log_head()


class TestHardening:
    """Round-2 guards: frozen update set, finite scores, hostile op bounds
    (advisor findings: post-close uploads desynchronized score-row lengths
    into an OOB read; apply-op length fields were trusted before allocation;
    NaN scores broke sort ordering)."""

    def _close_partial_round(self, led, n_uploads=4):
        fill_registration(led)
        run_upload_phase(led, n=n_uploads)
        assert led.update_count == n_uploads
        assert led.close_round() == LedgerStatus.OK

    def test_upload_rejected_after_close(self, led):
        self._close_partial_round(led)
        st = led.upload_local_update(addr(18), b"\2" * 32, 100, 1.0, 0)
        assert st == LedgerStatus.CAP_REACHED
        assert led.update_count == 4

    def test_upload_rejected_once_scoring_began(self, led):
        self._close_partial_round(led)
        assert led.upload_scores(led.committee()[0], 0,
                                 [0.5] * 4) == LedgerStatus.OK
        st = led.upload_local_update(addr(19), b"\3" * 32, 100, 1.0, 0)
        assert st == LedgerStatus.CAP_REACHED
        # the round still completes with consistent row lengths
        for c in led.committee()[1:]:
            assert led.upload_scores(c, 0, [0.5] * 4) == LedgerStatus.OK
        assert led.aggregate_ready()
        assert all(np.isfinite(led.pending().medians))
        assert led.commit_model(b"\4" * 32, 0) == LedgerStatus.OK

    def test_frozen_round_replays_identically(self, led):
        """The close -> score -> (rejected upload) -> commit sequence must
        replay to the same head on a fresh replica (the pre-fix crash made
        recovery permanently impossible)."""
        self._close_partial_round(led)
        for c in led.committee():
            led.upload_scores(c, 0, [0.5] * 4)
        led.upload_local_update(addr(19), b"\3" * 32, 100, 1.0, 0)  # rejected
        led.commit_model(b"\4" * 32, 0)
        replica = make_ledger(CFG, backend="python")
        for i in range(led.log_size()):
            assert replica.apply_op(led.log_op(i)) == LedgerStatus.OK
        assert replica.log_head() == led.log_head()

    @pytest.mark.parametrize("bad", [float("nan"), float("inf"),
                                     float("-inf"), 1e39])
    def test_nonfinite_scores_rejected(self, led, bad):
        # 1e39 is finite in float64 but overflows to inf in float32 — the
        # wire/native type — so it must be rejected too
        fill_registration(led)
        run_upload_phase(led)
        scores = [0.5] * 10
        scores[3] = bad
        assert led.upload_scores(led.committee()[0], 0,
                                 scores) == LedgerStatus.BAD_ARG
        assert led.score_count == 0

    def _prep_epoch0(self, led):
        fill_registration(led)
        run_upload_phase(led)

    def test_hostile_scores_op_bounded(self, led):
        """OP_SCORES claiming 2^60 floats must be rejected, not allocated."""
        import struct
        self._prep_epoch0(led)
        sender = addr(0).encode()
        op = bytes([3]) + struct.pack("<q", len(sender)) + sender
        op += struct.pack("<q", 0)          # epoch
        op += struct.pack("<q", 1 << 60)    # claimed length
        op += struct.pack("<f", 0.5)        # far fewer bytes than claimed
        assert led.apply_op(op) == LedgerStatus.BAD_ARG

    def test_hostile_reseat_op_bounded(self, led):
        """OP_RESEAT with an unbounded count must not loop/allocate."""
        import struct
        self._prep_epoch0(led)
        op = bytes([7]) + struct.pack("<q", 0)       # epoch
        op += struct.pack("<q", 1 << 60)             # claimed address count
        op += struct.pack("<q", 1) + b"x"
        assert led.apply_op(op) == LedgerStatus.BAD_ARG

    def test_truncated_trailing_string_rejected(self, led):
        """A string length running past the op must be BAD_ARG on both
        backends (Python slices used to silently truncate)."""
        import struct
        a = addr(0).encode()
        op = bytes([1]) + struct.pack("<q", len(a) + 50) + a  # claims 50 extra
        assert led.apply_op(op) == LedgerStatus.BAD_ARG
