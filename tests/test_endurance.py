"""The declared metric axis, measured (VERDICT r5 missing #2).

BASELINE.json declares the benchmark metric as "FL round time (s) + global
test-acc @ round 50" — and until this test nothing anywhere ran 50 rounds
(bench.py ran 26, e2e tests 3-12).  This is the 50-round CPU endurance
campaign: reference-equivalence config 1 end to end, with the property the
blockchain-as-checkpoint architecture exists to guarantee asserted rather
than assumed — strictly monotone epoch progress across the whole run.
Wired into bench.py via BFLC_BENCH_ENDURANCE=1 (the same
eval.benchmarks.endurance_config1 produces the artifact's `endurance`
block).
"""

import pytest

from bflc_demo_tpu.data.occupancy import occupancy_source
from bflc_demo_tpu.eval.benchmarks import endurance_config1

# real CSV: the reference's 0.9214 plateau band.  Synthetic stand-in (no
# CSV on this host): its raw-feature fixed-lr trajectory oscillates around
# a lower plateau (see tests/test_e2e.py ACC_BAR note) — bars calibrate to
# the source, both far above the 0.787 majority-class floor.
_REAL = occupancy_source() == "csv"
BEST_BAR = 0.92 if _REAL else 0.85
TAIL_BAR = 0.90 if _REAL else 0.80      # mean over rounds 41-50


@pytest.mark.slow
def test_endurance_at_snapshot_scale_wal_bounded():
    """ROADMAP "endurance at snapshot scale": a snapshot_interval-armed
    leg over 240 rounds must hold the WAL to a bounded sawtooth (the
    ceiling over the second half no higher than the first — a ramp
    would fail this) while the unarmed legacy journal grows linearly
    with the chain; rides the same endurance_config1 artifact
    (out["wal"]) with a short accuracy campaign attached."""
    out = endurance_config1(rounds=6, rounds_per_dispatch=3,
                            snapshot_interval=16, wal_rounds=240)
    assert out["rounds_completed"] == 6 and out["epochs_monotone"], out
    w = out["wal"]
    assert w["rounds"] >= 200, w
    # bounded vs linear: at 240 rounds / 16-round snapshots the armed
    # journal's CEILING must sit far under the legacy journal's final
    # size (the exact ratio grows with rounds; 4x is a conservative
    # floor at this geometry — measured ~15x)
    assert w["armed_max_wal_bytes"] * 4 < w["legacy_final_wal_bytes"], w
    # sawtooth, not a ramp: the second half's ceiling does not exceed
    # the first half's (+ one op of slack for commit-size jitter)
    assert w["armed_second_half_max_wal_bytes"] <= \
        w["armed_first_half_max_wal_bytes"] + 512, w
    # and the chain state itself is compacted behind the snapshots
    assert w["armed_held_ops"] < w["legacy_held_ops"], w


@pytest.mark.slow
def test_fifty_round_campaign_monotone_epochs_and_acc():
    out = endurance_config1(rounds=50)
    assert out["rounds_completed"] == 50, out
    # one sponsor observation per round, every one advancing the epoch:
    # no lost, stalled, or replayed round across the campaign
    assert out["epochs_monotone"], out
    # the round-50 plateau (BASELINE.json's metric axis), measured as the
    # last-10-round mean — the oscillation-robust estimate; a drifting or
    # diverging aggregation would sink it long before round 50
    assert out["tail10_mean_test_acc"] >= TAIL_BAR, out
    assert out["best_test_acc"] >= BEST_BAR, out
    # the declared point metric is recorded in the artifact regardless
    assert out["test_acc_at_round_50"] > 0.0, out
