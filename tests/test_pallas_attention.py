"""Flash-attention kernel tests (interpreter mode on CPU; same code
compiles natively on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.ops.pallas_attention import (flash_attention,
                                                _reference_attention)


def _qkv(rng, b=2, s=64, h=4, d=32):
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("block", [16, 32, 64])
    def test_matches_reference(self, block):
        rng = np.random.default_rng(0)
        q, k, v = _qkv(rng)
        mask = jnp.ones((2, 64), bool)
        got = flash_attention(q, k, v, mask, block_q=block, block_k=block,
                              interpret=True)
        want = _reference_attention(q, k, v, mask, 1.0 / np.sqrt(32))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_padding_mask(self):
        """PAD keys excluded exactly; changing a PAD key's value is
        invisible."""
        rng = np.random.default_rng(1)
        q, k, v = _qkv(rng)
        mask = np.ones((2, 64), bool)
        mask[:, 40:] = False
        mask = jnp.asarray(mask)
        got = flash_attention(q, k, v, mask, block_q=16, block_k=16,
                              interpret=True)
        want = _reference_attention(q, k, v, mask, 1.0 / np.sqrt(32))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        k2 = k.at[:, 50].set(999.0)          # PAD region
        v2 = v.at[:, 50].set(-999.0)
        got2 = flash_attention(q, k2, v2, mask, block_q=16, block_k=16,
                               interpret=True)
        np.testing.assert_allclose(got2, got, rtol=1e-6)

    def test_block_fully_masked(self):
        """A whole K block of PAD must not produce NaNs (the
        exp(NEG_INF - NEG_INF) case the online softmax guards)."""
        rng = np.random.default_rng(2)
        q, k, v = _qkv(rng)
        mask = np.ones((2, 64), bool)
        mask[:, 16:32] = False               # exactly one 16-block all PAD
        got = flash_attention(q, k, v, jnp.asarray(mask), block_q=16,
                              block_k=16, interpret=True)
        assert np.isfinite(np.asarray(got)).all()
        want = _reference_attention(q, k, v, jnp.asarray(mask),
                                    1.0 / np.sqrt(32))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_gradients_match_reference(self):
        rng = np.random.default_rng(3)
        q, k, v = _qkv(rng, b=1, s=32, h=2, d=16)
        mask = np.ones((1, 32), bool)
        mask[:, 28:] = False
        mask = jnp.asarray(mask)

        def loss_flash(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, mask, 16, 16,
                                           True) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_reference_attention(
                q_, k_, v_, mask, 1.0 / np.sqrt(16)) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-5)

    def test_gradients_match_reference_multiblock(self):
        """Blockwise dq/dk/dv across MANY (q, k) tiles — 4x4 blocks, b=2,
        h=4, ragged padding — against einsum autodiff."""
        rng = np.random.default_rng(7)
        q, k, v = _qkv(rng, b=2, s=64, h=4, d=32)
        mask = np.ones((2, 64), bool)
        mask[0, 50:] = False
        mask[1, 23:] = False                 # cuts inside a 16-block
        mask = jnp.asarray(mask)
        g = jnp.asarray(rng.standard_normal((2, 64, 4, 32)), jnp.float32)

        def run(fn):
            out, vjp = jax.vjp(fn, q, k, v)
            return (out, *vjp(g))

        of, dqf, dkf, dvf = run(lambda q_, k_, v_: flash_attention(
            q_, k_, v_, mask, 16, 16, True))
        orr, dqr, dkr, dvr = run(lambda q_, k_, v_: _reference_attention(
            q_, k_, v_, mask, 1.0 / np.sqrt(32)))
        np.testing.assert_allclose(of, orr, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(dqf, dqr, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(dkf, dkr, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(dvf, dvr, rtol=1e-4, atol=1e-4)

    def test_pad_positions_get_zero_grad(self):
        """dK/dV at PAD key positions must be exactly zero (those keys
        never contribute to any output), and dQ rows are independent of
        PAD key values."""
        rng = np.random.default_rng(8)
        q, k, v = _qkv(rng, b=1, s=32, h=2, d=16)
        mask = np.ones((1, 32), bool)
        mask[:, 16:] = False                 # second 16-block all PAD
        mask = jnp.asarray(mask)

        def loss(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, mask, 16, 16,
                                           True) ** 2)

        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert np.all(np.asarray(dk)[:, 16:] == 0.0)
        assert np.all(np.asarray(dv)[:, 16:] == 0.0)
        assert np.isfinite(np.asarray(dq)).all()

    def test_gradients_bf16(self):
        """bf16 storage dtype: gradients stay finite and track the f32
        reference within bf16 tolerance."""
        rng = np.random.default_rng(9)
        qf, kf, vf = _qkv(rng, b=1, s=32, h=2, d=16)
        mask = jnp.ones((1, 32), bool)
        q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))

        def loss(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, mask, 16, 16,
                                           True).astype(jnp.float32) ** 2)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_reference_attention(
                q_, k_, v_, mask, 1.0 / np.sqrt(16)).astype(jnp.float32) ** 2)

        gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(qf, kf, vf)
        for a, b in zip(gf, gr):
            assert np.isfinite(np.asarray(a, np.float32)).all()
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b), rtol=0.1, atol=0.15)

    def test_bad_block_size_rejected(self):
        rng = np.random.default_rng(4)
        q, k, v = _qkv(rng, s=60)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, jnp.ones((2, 60), bool), 16, 16, True)


class TestShardedFlashAttention:
    def test_tp_head_sharding_matches_unsharded(self):
        """The kernel under shard_map with heads over 'tp' (+ batch over
        'dp') — values AND gradients must match the single-device kernel."""
        from jax.sharding import Mesh
        from bflc_demo_tpu.ops.pallas_attention import sharded_flash_attention

        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("dp", "tp"))
        rng = np.random.default_rng(11)
        q, k, v = _qkv(rng, b=2, s=32, h=4, d=16)
        mask = np.ones((2, 32), bool)
        mask[:, 28:] = False
        mask = jnp.asarray(mask)

        def loss_sharded(q_, k_, v_):
            return jnp.sum(sharded_flash_attention(
                mesh, q_, k_, v_, mask, head_axis="tp", batch_axis="dp",
                block_q=16, block_k=16, interpret=True) ** 2)

        def loss_local(q_, k_, v_):
            return jnp.sum(flash_attention(q_, k_, v_, mask, 16, 16,
                                           True) ** 2)

        np.testing.assert_allclose(loss_sharded(q, k, v),
                                   loss_local(q, k, v), rtol=1e-5)
        gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
        gl = jax.grad(loss_local, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gs, gl):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_indivisible_heads_rejected(self):
        from jax.sharding import Mesh
        from bflc_demo_tpu.ops.pallas_attention import sharded_flash_attention

        mesh = Mesh(np.array(jax.devices()[:8]), ("tp",))
        rng = np.random.default_rng(12)
        q, k, v = _qkv(rng, b=1, s=16, h=4, d=16)
        with pytest.raises(ValueError):
            sharded_flash_attention(mesh, q, k, v, jnp.ones((1, 16), bool),
                                    head_axis="tp", interpret=True)


class TestTransformerIntegration:
    def test_transformer_with_pallas_attention(self):
        """attention_impl='pallas_interpret' swaps the transformer's core;
        logits must match the einsum path with identical params."""
        from bflc_demo_tpu.models import transformer as T
        model = T.make_transformer_classifier(vocab_size=100, seq_len=32,
                                              num_classes=3, dim=32,
                                              depth=1, heads=2)
        kernel_model = T.make_transformer_classifier(
            vocab_size=100, seq_len=32, num_classes=3, dim=32, depth=1,
            heads=2, attention_impl="pallas_interpret")
        rng = np.random.default_rng(5)
        toks = np.asarray(rng.integers(1, 100, (3, 32)), np.int32)
        toks[:, 20:] = 0
        toks = jnp.asarray(toks)
        params = model.init_params(0)
        want = model.apply(params, toks)
        got = kernel_model.apply(params, toks)
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-5)

    def test_env_read_at_construction_not_trace(self, monkeypatch):
        """The env flag affects models built AFTER it is set, never cached
        traces of existing models (the trace-time-latch hazard)."""
        from bflc_demo_tpu.models import transformer as T
        monkeypatch.setenv("BFLC_PALLAS_ATTENTION", "interpret")
        m = T.make_transformer_classifier(vocab_size=64, seq_len=16,
                                          num_classes=2, dim=16, depth=1,
                                          heads=2)
        assert m.config.attention_impl == "pallas_interpret"
        monkeypatch.delenv("BFLC_PALLAS_ATTENTION")
        m2 = T.make_transformer_classifier(vocab_size=64, seq_len=16,
                                           num_classes=2, dim=16, depth=1,
                                           heads=2)
        assert m2.config.attention_impl == "einsum"
        # the first model keeps its construction-time choice
        assert m.config.attention_impl == "pallas_interpret"
