"""On-device fingerprint tests: determinism, sensitivity, composability."""

import jax
import jax.numpy as jnp
import numpy as np

from bflc_demo_tpu.ops import (fingerprint_pytree, fingerprint_stacked,
                               fingerprint_to_bytes)


def tree(v=1.0):
    return {"W": jnp.full((5, 2), v, jnp.float32),
            "b": jnp.arange(2, dtype=jnp.float32)}


def test_deterministic():
    a = np.asarray(fingerprint_pytree(tree()))
    b = np.asarray(fingerprint_pytree(tree()))
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.uint32 and a.shape == (8,)


def test_value_dtype_shape_sensitive():
    base = np.asarray(fingerprint_pytree(tree()))
    assert not np.array_equal(base, fingerprint_pytree(tree(1.0 + 1e-7)))
    bf16 = {"W": tree()["W"].astype(jnp.bfloat16), "b": tree()["b"]}
    assert not np.array_equal(base, np.asarray(fingerprint_pytree(bf16)))
    reshaped = {"W": tree()["W"].reshape(2, 5), "b": tree()["b"]}
    assert not np.array_equal(base, np.asarray(fingerprint_pytree(reshaped)))


def test_leaf_boundary_sensitive():
    """Moving a value across leaves must change the digest (length salt)."""
    a = {"p": jnp.asarray([1.0, 2.0, 3.0]), "q": jnp.asarray([4.0])}
    b = {"p": jnp.asarray([1.0, 2.0]), "q": jnp.asarray([3.0, 4.0])}
    assert not np.array_equal(np.asarray(fingerprint_pytree(a)),
                              np.asarray(fingerprint_pytree(b)))


def test_stacked_matches_per_slice():
    rng = np.random.default_rng(0)
    stacked = {"W": jnp.asarray(rng.standard_normal((6, 5, 2)), jnp.float32),
               "b": jnp.asarray(rng.standard_normal((6, 2)), jnp.float32)}
    fps = np.asarray(fingerprint_stacked(stacked))
    assert fps.shape == (6, 8)
    for i in range(6):
        one = {"W": stacked["W"][i], "b": stacked["b"][i]}
        np.testing.assert_array_equal(fps[i],
                                      np.asarray(fingerprint_pytree(one)))
    # distinct slices -> distinct digests
    assert len({fps[i].tobytes() for i in range(6)}) == 6


def test_jit_consistency():
    direct = np.asarray(fingerprint_pytree(tree()))
    jitted = np.asarray(jax.jit(fingerprint_pytree)(tree()))
    np.testing.assert_array_equal(direct, jitted)


def test_to_bytes():
    b = fingerprint_to_bytes(fingerprint_pytree(tree()))
    assert isinstance(b, bytes) and len(b) == 32
    import pytest
    with pytest.raises(ValueError):
        fingerprint_to_bytes(np.zeros(4, np.uint32))
