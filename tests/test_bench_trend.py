"""Satellite tooling: the BENCH_r*.json trajectory collector
(tools/bench_trend.py) over the checked-in artifacts + its regression
flagging, and the tier-1 budget enforcer (tools/check_tier1_budget.py)
that makes the slow-marking policy checkable instead of manual."""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


class TestBenchTrend:
    def test_parses_checked_in_artifacts(self, capsys):
        bt = _tool("bench_trend")
        series = bt.load_series(REPO)
        assert len(series) >= 5
        assert [n for n, _ in series] == sorted(n for n, _ in series)
        report = bt.trend(series)
        # accuracy is the stable axis on this host: present for every
        # artifact and never regressed across the trajectory
        accs = dict(report["metrics"]["best_test_acc"])
        assert len(accs) == len(series)
        assert all(r["metric"] != "best_test_acc"
                   for r in report["regressions"])
        assert bt.main([REPO]) == 0          # non-strict always renders
        out = capsys.readouterr().out
        assert "best_test_acc" in out

    def test_regression_flagged_vs_best_prior(self, tmp_path):
        bt = _tool("bench_trend")
        rounds = [
            (1, {"metric": "m", "value": 1.0,
                 "extra": {"best_test_acc": 0.90}}),
            (2, {"metric": "m", "value": 0.5,
                 "extra": {"best_test_acc": 0.92}}),
            # value (lower-better) regresses 40% vs best prior (0.5);
            # accuracy (higher-better) regresses vs best prior (0.92)
            (3, {"metric": "m", "value": 0.7,
                 "extra": {"best_test_acc": 0.70}}),
        ]
        for n, rec in rounds:
            with open(tmp_path / f"BENCH_r{n:02d}.json", "w") as fh:
                json.dump({"n": n, "parsed": rec}, fh)
        report = bt.trend(bt.load_series(str(tmp_path)),
                          threshold=0.10)
        flagged = {(r["metric"], r["round"])
                   for r in report["regressions"]}
        assert ("round_time_s", 3) in flagged
        assert ("best_test_acc", 3) in flagged
        assert ("round_time_s", 2) not in flagged   # improvement
        # --strict turns flags into a failing exit code
        assert bt.main([str(tmp_path), "--strict"]) == 1
        # both regressions (40% and ~24%) sit under a 50% threshold
        report50 = bt.trend(bt.load_series(str(tmp_path)),
                            threshold=0.50)
        assert report50["regressions"] == []

    def test_signed_near_zero_fracs_use_absolute_deltas(self, tmp_path):
        """Review regression: overhead fractions hover around 0 — a
        relative test against a near-zero best manufactures huge
        spurious percentages from noise.  They flag on ABSOLUTE
        change only."""
        bt = _tool("bench_trend")
        rounds = [
            (1, {"metric": "m", "value": 1.0, "extra": {
                "trace_overhead": {"overhead_frac": -0.02}}}),
            # +5 percentage points of noise: NOT a regression at 0.10
            (2, {"metric": "m", "value": 1.0, "extra": {
                "trace_overhead": {"overhead_frac": 0.03}}}),
            # +17 points over the best prior (-0.02): flagged
            (3, {"metric": "m", "value": 1.0, "extra": {
                "trace_overhead": {"overhead_frac": 0.15}}}),
        ]
        for n, rec in rounds:
            with open(tmp_path / f"BENCH_r{n:02d}.json", "w") as fh:
                json.dump({"n": n, "parsed": rec}, fh)
        report = bt.trend(bt.load_series(str(tmp_path)),
                          threshold=0.10)
        flagged = {(r["metric"], r["round"])
                   for r in report["regressions"]}
        assert ("trace_overhead_frac", 2) not in flagged
        assert ("trace_overhead_frac", 3) in flagged

    def test_empty_dir_errors(self, tmp_path):
        bt = _tool("bench_trend")
        assert bt.main([str(tmp_path)]) == 2


_LOG = """\
============================= slowest durations ==============================
25.01s call     tests/test_big.py::TestX::test_heavy
0.50s setup    tests/test_big.py::TestX::test_heavy
12.30s call     tests/test_small.py::test_quick
0.01s teardown tests/test_small.py::test_quick
=========================== 2 passed in 38.12s ===========================
"""

_LOG_OVER = _LOG.replace("25.01s", "45.01s")


class TestCheckTier1Budget:
    def test_durations_summed_per_nodeid(self, tmp_path):
        cb = _tool("check_tier1_budget")
        per_test, wall, passed = cb.parse_log(_LOG)
        assert per_test["tests/test_big.py::TestX::test_heavy"] == \
            25.51
        assert per_test["tests/test_small.py::test_quick"] == 12.31
        assert wall == 38.12 and passed == 2
        report = cb.check(per_test, wall, budget=870.0, limit=30.0)
        assert report["over_limit"] == []
        assert report["budget_used_frac"] == round(38.12 / 870.0, 3)

    def test_unmarked_test_over_limit_fails(self, tmp_path, capsys):
        cb = _tool("check_tier1_budget")
        log = tmp_path / "t1.log"
        log.write_text(_LOG_OVER)
        assert cb.main([str(log)]) == 1
        out = capsys.readouterr().out
        assert "OVER LIMIT" in out and "test_heavy" in out
        # raising the ceiling clears it
        assert cb.main([str(log), "--limit", "60"]) == 0

    def test_no_duration_lines_is_an_error(self, tmp_path):
        cb = _tool("check_tier1_budget")
        log = tmp_path / "empty.log"
        log.write_text("2 passed in 1.00s\n")
        assert cb.main([str(log)]) == 2
