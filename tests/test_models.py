"""Model zoo contract tests: every family trains, scores and aggregates
under the exact same generic FL machinery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.core import local_train, evaluate, score_candidates
from bflc_demo_tpu.models import (REGISTRY, make_mlp, make_lenet5,
                                  make_femnist_cnn, make_resnet18)
from bflc_demo_tpu.models.transformer import make_transformer_classifier

SMALL = {
    "mlp": lambda: make_mlp((8, 8, 1), hidden=32, num_classes=4),
    "lenet5": lambda: make_lenet5((16, 16, 3), num_classes=4),
    "femnist_cnn": lambda: make_femnist_cnn((16, 16, 1), num_classes=6),
    "resnet18": lambda: make_resnet18((16, 16, 3), num_classes=4),
    "transformer": lambda: make_transformer_classifier(
        vocab_size=50, seq_len=12, num_classes=3, dim=16, depth=1, heads=2),
}


def _batch(model, n, rng):
    if model.name == "transformer":
        x = rng.integers(1, 50, (n,) + model.input_shape).astype(np.int32)
    else:
        x = rng.random((n,) + model.input_shape).astype(np.float32)
    y = np.eye(model.num_classes, dtype=np.float32)[
        rng.integers(0, model.num_classes, n)]
    return jnp.asarray(x), jnp.asarray(y)


@pytest.mark.parametrize("name", list(SMALL))
def test_forward_shapes_and_determinism(name):
    model = SMALL[name]()
    rng = np.random.default_rng(0)
    x, _ = _batch(model, 4, rng)
    params = model.init_params(0)
    logits = model.apply(params, x)
    assert logits.shape == (4, model.num_classes)
    assert logits.dtype == jnp.float32
    np.testing.assert_array_equal(logits, model.apply(params, x))


@pytest.mark.parametrize("name", list(SMALL))
def test_local_train_and_score_generic(name):
    """The FL triangle is model-agnostic: train -> delta, score candidates."""
    model = SMALL[name]()
    rng = np.random.default_rng(1)
    x, y = _batch(model, 32, rng)
    params = model.init_params(0)
    delta, cost = local_train(model.apply, params, x, y, lr=0.05,
                              batch_size=16)
    assert np.isfinite(float(cost))
    stacked = jax.tree_util.tree_map(
        lambda d: jnp.stack([d, jnp.zeros_like(d)]), delta)
    scores = score_candidates(model.apply, params, stacked, 0.05, x, y)
    assert scores.shape == (2,)
    # candidate 1 has zero delta == the global model itself
    np.testing.assert_allclose(
        scores[1], evaluate(model.apply, params, x, y), rtol=1e-6)


def test_registry_complete():
    assert set(REGISTRY) == {"softmax_regression", "mlp", "lenet5",
                             "femnist_cnn", "resnet18"}


def test_bfloat16_compute_path():
    """MXU-native bf16 compute with f32 params/logits: the whole FL triangle
    (train -> score -> fingerprint) runs and stays finite."""
    model = make_lenet5((16, 16, 3), num_classes=4, dtype=jnp.bfloat16)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.random((64, 16, 16, 3)), jnp.float32)
    y = jnp.asarray(np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)])
    params = model.init_params(0)
    logits = model.apply(params, x)
    assert logits.dtype == jnp.float32          # head stays f32
    delta, cost = local_train(model.apply, params, x, y, lr=0.05,
                              batch_size=32)
    assert np.isfinite(float(cost))
    stacked = jax.tree_util.tree_map(
        lambda d: jnp.stack([d, jnp.zeros_like(d)]), delta)
    scores = score_candidates(model.apply, params, stacked, 0.05, x, y)
    assert np.isfinite(np.asarray(scores)).all()
    from bflc_demo_tpu.ops import fingerprint_pytree
    fp = np.asarray(fingerprint_pytree(delta))
    assert fp.shape == (8,)


def test_mlp_learns_synthetic():
    model = make_mlp((8, 8, 1), hidden=64, num_classes=4)
    from bflc_demo_tpu.data.synthetic import synthetic_image_classification
    x, y = synthetic_image_classification(600, (8, 8, 1), 4, seed=0)
    xj, yj = jnp.asarray(x), jnp.asarray(np.eye(4, dtype=np.float32)[y])
    params = model.init_params(0)
    delta, _ = local_train(model.apply, params, xj, yj, lr=0.1,
                           batch_size=60, local_epochs=20)
    trained = jax.tree_util.tree_map(lambda p, d: p - 0.1 * d, params, delta)
    assert float(evaluate(model.apply, trained, xj, yj)) > 0.8
