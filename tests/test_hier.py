"""Hierarchical cell federation (bflc_demo_tpu.hier).

Four layers:
- unit: deterministic cell planning + tier protocol derivation;
- the determinism PROPERTY: the same admitted delta set produces
  byte-identical partial-sum canonical bytes (and hash) under every
  arrival order — the cell-aggregate op's content address is a pure
  function of the admitted set;
- root admission + certification: a cell-aggregate op rides the
  UNCHANGED upload/BFT machinery (`verify_certificate` byte-compatible),
  while a forged partial (wrong hash) or an inflated client count
  (beyond registered membership) fails both at the root writer and at an
  honest validator;
- e2e: a real two-tier OS-process federation (2 cells x 3 members)
  completes rounds and converges through the root's committed model.
"""

import hashlib
import itertools
import struct

import numpy as np
import pytest

from bflc_demo_tpu.hier.cells import (CellPlan, cell_protocol, cell_seed,
                                      plan_cells, root_protocol)
from bflc_demo_tpu.hier.partial import (CELLMETA_KEY, cell_evidence_digest,
                                        cell_partial, check_cell_upload_op,
                                        pack_cellmeta, partial_blob,
                                        split_cellmeta, unpack_cellmeta)
from bflc_demo_tpu.ledger.base import encode_upload_op
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils.serialization import (pack_pytree, unpack_pytree)


class TestCellPlan:
    def test_deterministic_and_covering(self):
        a = plan_cells(20, cells=4)
        b = plan_cells(20, cells=4)
        assert a == b
        flat = [i for m in a.members for i in m]
        assert sorted(flat) == list(range(20))
        assert a.n_cells == 4
        assert all(len(m) == 5 for m in a.members)
        assert a.cell_of(0) == 0 and a.cell_of(19) == 3
        assert a.sibling_of(3) == 0

    def test_remainder_spread(self):
        p = plan_cells(10, cells=3)
        assert [len(m) for m in p.members] == [4, 3, 3]

    def test_cell_size_route(self):
        p = plan_cells(20, cell_size=5)
        assert p.n_cells == 4
        assert plan_cells(20, cells=4, cell_size=5).members == p.members

    def test_rejects_bad_geometry(self):
        with pytest.raises(ValueError):
            plan_cells(20)                      # neither knob
        with pytest.raises(ValueError):
            plan_cells(20, cells=1)             # no root committee
        with pytest.raises(ValueError):
            plan_cells(20, cells=15)            # 1-member cells
        with pytest.raises(ValueError):
            plan_cells(20, cells=4, cell_size=2)

    def test_tier_protocols_validate(self):
        base = ProtocolConfig()
        for n_members in (2, 3, 5, 10):
            cc = cell_protocol(base, n_members)
            assert cc.client_num == n_members
            assert cc.validate() is cc
        for n_cells in (2, 3, 8, 100):
            rc = root_protocol(base, n_cells)
            assert rc.client_num == n_cells
            assert rc.delta_dtype == "f32"
            assert rc.validate() is rc
            # full coverage: every non-committee cell's partial admits
            assert rc.needed_update_count == n_cells - rc.comm_count

    def test_cell_seed_distinct(self):
        seeds = {cell_seed(b"m", c) for c in range(16)}
        assert len(seeds) == 16

    def test_plan_is_frozen(self):
        p = plan_cells(8, cells=2)
        assert isinstance(p, CellPlan)
        with pytest.raises(Exception):
            p.n_clients = 9


def _member_delta(v, shape=(3, 2)):
    return unpack_pytree(pack_pytree(
        {"W": np.full(shape, v, np.float32),
         "b": np.arange(shape[1], dtype=np.float32) * v}))


class TestPartialDeterminism:
    """Satellite: same admitted deltas in ANY arrival order produce
    byte-identical partial-sum canonical bytes and hash."""

    def test_arrival_order_independence(self):
        admitted = [(f"0x{i:040x}", _member_delta(0.37 * (i + 1)),
                     10 + 3 * i, 1.0 + i) for i in range(4)]
        digests = set()
        blobs = set()
        for perm in itertools.permutations(admitted):
            part, n, cost = cell_partial(list(perm))
            ev = cell_evidence_digest(
                5, 2, [(a, b"\7" * 32, nn, cc) for a, _, nn, cc in perm],
                [0.5, 0.25, 0.75, 0.5], [2, 0, 1, 3])
            blob = partial_blob(part, 2, n, ev)
            blobs.add(blob)
            digests.add(hashlib.sha256(blob).hexdigest())
        assert len(blobs) == 1 and len(digests) == 1

    def test_weighting_is_sample_weighted_fedavg(self):
        a = (f"0xa", _member_delta(1.0), 30, 1.0)
        b = (f"0xb", _member_delta(2.0), 10, 3.0)
        part, n, cost = cell_partial([a, b])
        assert n == 2
        key = [k for k in part if k.endswith("'W']")][0]
        # (30*1 + 10*2) / 40 = 1.25
        assert np.allclose(np.asarray(part[key]), 1.25)
        assert cost == pytest.approx(2.0)

    def test_rejects_degenerate_sets(self):
        with pytest.raises(ValueError):
            cell_partial([])
        d = ("0xa", _member_delta(1.0), 10, 1.0)
        with pytest.raises(ValueError):
            cell_partial([d, d])                # duplicate sender
        with pytest.raises(ValueError):
            cell_partial([("0xa", _member_delta(1.0), 0, 1.0)])
        with pytest.raises(ValueError):
            cell_partial([d, ("0xb", {"other": np.zeros(2, np.float32)},
                              5, 1.0)])         # key mismatch

    def test_evidence_digest_sensitivity(self):
        rec = [("0xa", b"\1" * 32, 10, 1.0)]
        base = cell_evidence_digest(0, 0, rec, [0.5], [0])
        assert cell_evidence_digest(0, 0, list(reversed(rec)),
                                    [0.5], [0]) == base
        assert cell_evidence_digest(1, 0, rec, [0.5], [0]) != base
        assert cell_evidence_digest(0, 1, rec, [0.5], [0]) != base
        assert cell_evidence_digest(0, 0, rec, [0.6], [0]) != base


class TestCellMeta:
    def test_roundtrip(self):
        ev = hashlib.sha256(b"evidence").digest()
        arr = pack_cellmeta(3, 17, ev)
        assert unpack_cellmeta(arr) == (3, 17, ev)

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            unpack_cellmeta(np.zeros(57, np.uint8))
        with pytest.raises(ValueError):
            pack_cellmeta(0, 1, b"short")
        with pytest.raises(ValueError):
            pack_cellmeta(0, 0, b"\0" * 32)

    def test_split(self):
        ev = b"\5" * 32
        part = _member_delta(1.0)
        blob = partial_blob(part, 1, 4, ev)
        flat = unpack_pytree(blob)
        assert CELLMETA_KEY in flat
        rest, meta = split_cellmeta(flat)
        assert meta == (1, 4, ev)
        assert CELLMETA_KEY not in rest
        assert rest.keys() == part.keys()
        # no meta entry -> passthrough
        rest2, meta2 = split_cellmeta(part)
        assert meta2 is None and rest2.keys() == part.keys()

    def test_check_cell_upload_op(self):
        op = encode_upload_op("0xagg", b"\1" * 32, 5, 1.0, 0)
        assert check_cell_upload_op(op, {"0xagg": (0, 5)}) == ""
        assert "exceeds registered membership" in \
            check_cell_upload_op(op, {"0xagg": (0, 4)})
        assert "not a registered cell aggregator" in \
            check_cell_upload_op(op, {"0xother": (1, 10)})
        # non-upload ops pass through untouched
        assert check_cell_upload_op(b"\x01rest", {}) == ""
        assert check_cell_upload_op(b"", {}) == ""


# ------------------------------------------------ root admission + BFT
def _model0():
    return {"W": np.zeros((5, 2), np.float32),
            "b": np.zeros((2,), np.float32)}


def _sign(w, kind, epoch, payload):
    from bflc_demo_tpu.comm.identity import _op_bytes
    return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()


@pytest.fixture()
def root_fleet():
    """Thread-served root with 4 validators and a 4-cell registry."""
    from bflc_demo_tpu.comm.bft import ValidatorNode, provision_validators
    from bflc_demo_tpu.comm.identity import Wallet
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)

    base = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                          needed_update_count=4, learning_rate=0.05,
                          batch_size=16)
    rcfg = root_protocol(base, 4)
    wallets = {c: Wallet.from_seed(cell_seed(b"hier-test", c))
               for c in range(4)}
    registry = {w.address: (c, 2) for c, w in wallets.items()}
    vwallets, vkeys = provision_validators(4, b"hier-test-validators")
    nodes = [ValidatorNode(rcfg, w, i, validator_keys=vkeys,
                           cell_registry=registry)
             for i, w in enumerate(vwallets)]
    for v in nodes:
        v.start()
    srv = LedgerServer(rcfg, pack_pytree(_model0()),
                       cell_registry=registry, ledger_backend="python",
                       stall_timeout_s=60.0,
                       bft_validators=[(v.host, v.port) for v in nodes],
                       bft_keys=vkeys)
    srv.start()
    client = CoordinatorClient(srv.host, srv.port)
    yield srv, client, wallets, registry, vkeys, nodes
    client.close()
    srv.close()
    for v in nodes:
        v.close()


def _cell_op_blob(v=0.25, cell=0, n_clients=2, evidence=b"\0" * 32):
    adm = [(f"0xm{j}", unpack_pytree(pack_pytree(
        {"W": np.full((5, 2), v * (j + 1), np.float32),
         "b": np.zeros((2,), np.float32)})), 10, 1.0)
        for j in range(n_clients)]
    part, n, cost = cell_partial(adm)
    return partial_blob(part, cell, n_clients, evidence), n, cost


class TestRootAdmission:
    def test_honest_cell_op_certifies_byte_compatibly(self, root_fleet):
        """A cell-aggregate op is a STANDARD upload op: it gathers a
        quorum certificate that the UNCHANGED verify_certificate
        accepts, bound to the op reconstructed by the unchanged
        encode_upload_op."""
        from bflc_demo_tpu.comm.bft import (expected_op_hash,
                                            verify_certificate_sigs)
        srv, client, wallets, registry, vkeys, _ = root_fleet
        for c, w in wallets.items():
            r = client.request("register", addr=w.address,
                               pubkey=w.public_bytes.hex(),
                               tag=_sign(w, "register", 0, b""))
            assert r["ok"], r
        committee = set(client.request("committee")["committee"])
        trainer_cell, trainer = next(
            (c, w) for c, w in wallets.items()
            if w.address not in committee)
        blob, n, cost = _cell_op_blob(cell=trainer_cell)
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", n, cost)
        fields = dict(addr=trainer.address, hash=digest.hex(), n=n,
                      cost=cost, epoch=0)
        r = client.request("upload", blob=blob,
                           tag=_sign(trainer, "upload", 0, payload),
                           **fields)
        assert r["ok"], r
        # the ack's certificate verifies under the BYTE-COMPATIBLE
        # client-side check, bound to this exact op's reconstruction
        assert r.get("cert") is not None
        assert verify_certificate_sigs(
            r["cert"], 3, vkeys,
            op_hash=expected_op_hash("upload", fields))

    def test_forged_hash_rejected(self, root_fleet):
        srv, client, wallets, *_ = root_fleet
        w = wallets[0]
        client.request("register", addr=w.address,
                       pubkey=w.public_bytes.hex(),
                       tag=_sign(w, "register", 0, b""))
        blob, n, cost = _cell_op_blob()
        wrong = hashlib.sha256(b"not the blob").digest()
        payload = wrong + struct.pack("<qd", n, cost)
        r = client.request("upload", addr=w.address, blob=blob,
                           hash=wrong.hex(), n=n, cost=cost, epoch=0,
                           tag=_sign(w, "upload", 0, payload))
        assert not r["ok"] and r["status"] == "BAD_ARG"
        assert "mismatch" in r["error"]

    def test_inflated_count_rejected_at_root(self, root_fleet):
        srv, client, wallets, registry, *_ = root_fleet
        for w in wallets.values():
            client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=_sign(w, "register", 0, b""))
        w = next(iter(wallets.values()))
        # claims 1000 clients; registered membership is 2
        blob, _, cost = _cell_op_blob(n_clients=1)
        flat = unpack_pytree(blob)
        part, _ = split_cellmeta(flat)
        blob = partial_blob(part, 0, 1000, b"\0" * 32)
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", 1000, cost)
        r = client.request("upload", addr=w.address, blob=blob,
                           hash=digest.hex(), n=1000, cost=cost,
                           epoch=0, tag=_sign(w, "upload", 0, payload))
        assert not r["ok"] and r["status"] == "BAD_ARG"
        assert "exceeds registered membership" in r["error"]

    def test_meta_op_weight_mismatch_rejected(self, root_fleet):
        srv, client, wallets, *_ = root_fleet
        for w in wallets.values():
            client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=_sign(w, "register", 0, b""))
        w = next(iter(wallets.values()))
        blob, n, cost = _cell_op_blob(n_clients=2)     # meta says 2
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", 1, cost)
        r = client.request("upload", addr=w.address, blob=blob,
                           hash=digest.hex(), n=1, cost=cost, epoch=0,
                           tag=_sign(w, "upload", 0, payload))
        assert not r["ok"] and "!= op weight" in r["error"]

    def test_missing_cellmeta_rejected(self, root_fleet):
        srv, client, wallets, *_ = root_fleet
        for w in wallets.values():
            client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=_sign(w, "register", 0, b""))
        w = next(iter(wallets.values()))
        blob = pack_pytree(_model0())                  # no #cellmeta
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", 2, 1.0)
        r = client.request("upload", addr=w.address, blob=blob,
                           hash=digest.hex(), n=2, cost=1.0, epoch=0,
                           tag=_sign(w, "upload", 0, payload))
        assert not r["ok"] and "#cellmeta" in r["error"]

    def test_forged_cell_index_rejected(self, root_fleet):
        """A registered aggregator cannot attribute its partial to
        ANOTHER cell: admission binds the certified #cellmeta cell
        index to the sender's registered cell, so an audit keyed on
        the certified index cannot be poisoned."""
        srv, client, wallets, *_ = root_fleet
        for w in wallets.values():
            client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=_sign(w, "register", 0, b""))
        w = wallets[2]                          # registered as cell 2
        blob, n, cost = _cell_op_blob(cell=0)   # #cellmeta claims cell 0
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", n, cost)
        r = client.request("upload", addr=w.address, blob=blob,
                           hash=digest.hex(), n=n, cost=cost, epoch=0,
                           tag=_sign(w, "upload", 0, payload))
        assert not r["ok"] and r["status"] == "BAD_ARG"
        assert "!= registered cell" in r["error"]

    def test_unregistered_sender_rejected(self, root_fleet):
        from bflc_demo_tpu.comm.identity import Wallet
        srv, client, wallets, *_ = root_fleet
        for w in wallets.values():
            client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=_sign(w, "register", 0, b""))
        rogue = Wallet.from_seed(b"rogue-aggregator")
        r = client.request("register", addr=rogue.address,
                           pubkey=rogue.public_bytes.hex(),
                           tag=_sign(rogue, "register", 0, b""))
        blob, n, cost = _cell_op_blob()
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", n, cost)
        r = client.request("upload", addr=rogue.address, blob=blob,
                           hash=digest.hex(), n=n, cost=cost, epoch=0,
                           tag=_sign(rogue, "upload", 0, payload))
        assert not r["ok"]
        assert "not a registered cell aggregator" in r["error"]

    def test_validator_refuses_inflated_count_directly(self, root_fleet):
        """Even a COLLUDING root writer cannot certify an inflated cell
        weight: an honest validator holding the registry refuses the
        vote (the op-level half of the anti-inflation bound)."""
        from bflc_demo_tpu.comm.bft import ValidatorClient
        srv, client, wallets, registry, vkeys, nodes = root_fleet
        w = next(iter(wallets.values()))
        op = encode_upload_op(w.address, b"\x09" * 2 + b"\0" * 30,
                              1000, 1.0, 0)
        vc = ValidatorClient((nodes[0].host, nodes[0].port))
        try:
            r = vc.request("bft_validate", i=0, op=op.hex(),
                           auth={"tag": "", "n": 1000, "cost": 1.0})
            assert not r.get("ok")
            assert r.get("status") == "CELL", r
        finally:
            vc.close()


@pytest.mark.slow
class TestHierFederationE2E:
    """The two-tier deployment end to end: 2 cells x 3 members as real
    OS processes, the root committing a client-count-weighted merge of
    certified cell partials, the global model flowing back down through
    the aggregators to every member."""

    def test_two_cell_federation_converges(self, tmp_path):
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.hier.runtime import run_federated_hier

        cfg = ProtocolConfig(client_num=6, comm_count=2,
                             aggregate_count=2, needed_update_count=2,
                             learning_rate=0.05, batch_size=32,
                             local_epochs=2).validate()
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1800], ytr[:1800], cfg.client_num)
        res = run_federated_hier(
            "make_softmax_regression", shards, (xte[:400], yte[:400]),
            cfg, rounds=3, cells=2, timeout_s=280.0,
            telemetry_dir=str(tmp_path / "telemetry"))
        assert res.rounds_completed >= 3
        assert res.best_accuracy() > 0.70
        # every member finished its rounds loop cleanly
        assert all(c == 0 for c in res.client_exitcodes), \
            res.client_exitcodes
        # the telemetry plane covers the cell tier: cell roles answered
        # the scrape RPC with the cell-specific metrics
        from bflc_demo_tpu.obs.collector import load_timeline
        tl = load_timeline(res.telemetry_report["jsonl"])
        seen_cell_metrics = False
        for rec in tl:
            if rec.get("type") != "scrape":
                continue
            for role, snap in rec.get("roles", {}).items():
                if role.startswith("cell-") and \
                        (snap.get("metrics") or {}).get("cell_admitted"):
                    seen_cell_metrics = True
        assert seen_cell_metrics

    def test_bft_root_certifies_o_cells(self, tmp_path):
        """With a root validator quorum: every root op certifies, and
        the per-round root op count is O(cells) — upload(s) + score(s) +
        commit — independent of the member population."""
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.hier.runtime import run_federated_hier

        cfg = ProtocolConfig(client_num=6, comm_count=2,
                             aggregate_count=2, needed_update_count=2,
                             learning_rate=0.05, batch_size=32,
                             local_epochs=2).validate()
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1800], ytr[:1800], cfg.client_num)
        res = run_federated_hier(
            "make_softmax_regression", shards, (xte[:400], yte[:400]),
            cfg, rounds=2, cells=2, bft_validators=4, timeout_s=280.0)
        info = res.final_info
        assert res.rounds_completed >= 2
        assert info["certified_size"] == info["log_size"]
        # 2 registrations + rounds x (1 upload + 1 score + 1 commit):
        # O(cells)/round, nothing per-member ever reaches the root
        ops_per_round = (info["log_size"] - 2) / res.rounds_completed
        assert ops_per_round <= 2 * 3
