"""Validator re-derivation plane: the lying-writer drill + shard laws.

The acceptance spec for bflc_demo_tpu/rederive (ISSUE 15):

- a writer committing a WRONG model hash — sync commit AND async drain
  — fails certification under ``--rederive shard``, and a colluding
  lying validator within f cannot save it (the min(n, 2f+1)-way shard
  coverage guarantees >= f+1 honest refusals for any wrong leaf, which
  pushes the attainable signer count below the 2f+1 quorum);
- honest runs produce byte-identical committed hashes armed vs
  ``BFLC_REDERIVE_LEGACY=1`` (golden twin runs);
- a poisoned NaN delta that certifies garbage today is REFUSED when
  armed (the health-enforcement half);
- blob/evidence unavailability degrades to the guard-check with zero
  stalls, a counted skip and a flight WARN — never a wedge;
- the leaf-shard partition is a pure function of public inputs:
  deterministic across validators and rejoins, full coverage with
  >= 2-way overlap at every quorum geometry;
- a root-tier cell partial that is not the FedAvg of its member-signed
  deltas is refused (PARITY divergence 4's re-derivable half).
"""

import hashlib
import struct
import time
from unittest import mock

import numpy as np

import bflc_demo_tpu.comm.ledger_service as ls
from bflc_demo_tpu.comm.bft import ValidatorNode, provision_validators
from bflc_demo_tpu.comm.identity import Wallet, _op_bytes, provision_wallets
from bflc_demo_tpu.protocol.constants import (ProtocolConfig,
                                              bft_fault_tolerance,
                                              bft_quorum)
from bflc_demo_tpu.rederive import (REDERIVE_MODES, rederive_armed,
                                    rederive_mode)
from bflc_demo_tpu.rederive.shards import (leaf_owners, leaf_shard,
                                           shard_coverage, shard_map)
from bflc_demo_tpu.utils.serialization import (pack_entries, pack_pytree,
                                               unpack_pytree)

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)
N_VALIDATORS = 4        # the reference 4-node geometry: f=1, quorum=3


def _init_blob():
    return pack_pytree({"W": np.zeros((5, 2), np.float32),
                        "b": np.zeros((2,), np.float32)})


def _delta_tree(v):
    return {"W": np.full((5, 2), v, np.float32),
            "b": np.full((2,), v * 0.1, np.float32)}


def _sign(w, kind, epoch, payload):
    return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()


def _corrupting_pack(entries):
    """A self-consistent wrong model: the hash matches the (corrupted)
    blob, so only arithmetic re-derivation can catch it."""
    e = dict(entries)
    k = sorted(e)[0]
    a = np.array(e[k], np.float32).copy()
    a.flat[0] += np.float32(0.25)
    e[k] = a
    return pack_entries(e)


class _Fleet:
    """In-process writer + validator quorum with per-validator rederive
    modes — the drill harness (thread-served, real sockets)."""

    def __init__(self, modes, cfg=CFG, bft_timeout_s=1.5, seed=b"rd-01"):
        self.cfg = cfg
        self.init = _init_blob()
        vwallets, self.vkeys = provision_validators(len(modes), seed)
        self.nodes = [
            ValidatorNode(cfg, w, i, validator_keys=self.vkeys,
                          initial_model_blob=self.init,
                          rederive=modes[i])
            for i, w in enumerate(vwallets)]
        for v in self.nodes:
            v.start()
        self.server = ls.LedgerServer(
            cfg, self.init,
            bft_validators=[(v.host, v.port) for v in self.nodes],
            bft_keys=self.vkeys, bft_timeout_s=bft_timeout_s)
        self.server.start()
        self.client = ls.CoordinatorClient(self.server.host,
                                           self.server.port)
        self.wallets, _ = provision_wallets(cfg.client_num,
                                            seed + b"-clients")

    def register_all(self):
        for w in self.wallets:
            r = self.client.request(
                "register", addr=w.address,
                pubkey=w.public_bytes.hex(),
                tag=_sign(w, "register", 0, b""))
            assert r["ok"] or r["status"] in ("ALREADY_REGISTERED",
                                              "DUPLICATE"), r

    def drive_round(self, epoch, delta_of=None, scores_of=None):
        """One full sync round; returns the LAST scores reply (which
        carries the commit's certification outcome)."""
        committee = set(self.client.request("committee")["committee"])
        trainers = [w for w in self.wallets
                    if w.address not in committee]
        nu = self.cfg.needed_update_count
        for i, w in enumerate(trainers[:nu]):
            tree = (delta_of(i) if delta_of is not None
                    else _delta_tree(0.1 * (i + 1) + epoch))
            blob = pack_pytree(tree)
            d = hashlib.sha256(blob).digest()
            payload = d + struct.pack("<qd", 10 + i, 1.0)
            r = self.client.request(
                "upload", addr=w.address, blob=blob, hash=d.hex(),
                n=10 + i, cost=1.0, epoch=epoch,
                tag=_sign(w, "upload", epoch, payload))
            assert r["ok"] or r["status"] == "DUPLICATE", r
        last = None
        for j, w in enumerate([w for w in self.wallets
                               if w.address in committee]):
            row = (scores_of(j) if scores_of is not None
                   else [0.5 + 0.01 * (j + u) for u in range(nu)])
            payload = struct.pack(f"<{nu}d", *row)
            last = self.client.request(
                "scores", addr=w.address, epoch=epoch, scores=row,
                tag=_sign(w, "scores", epoch, payload))
        return last

    def model_hash(self):
        return self.client.request("model", meta=1)["hash"]

    def honest_stats(self):
        return [v._rederiver.stats for v in self.nodes
                if v._rederiver is not None]

    def close(self):
        self.client.close()
        self.server.close()
        for v in self.nodes:
            v.close()


# --------------------------------------------------- shard partition laws
class TestShardPartition:
    def test_coverage_rule(self):
        # the safety bar: coverage >= min(n, 2f+1), never below 2-way
        # overlap once two validators exist
        for n in (1, 2, 3, 4, 7, 10, 13):
            c = shard_coverage(n)
            f = bft_fault_tolerance(n)
            assert c >= min(n, 2 * f + 1)
            if n >= 2:
                assert c >= 2
            assert c <= n

    def test_union_covers_with_overlap_at_every_geometry(self):
        keys = [f"/leaf{j}" for j in range(11)]
        for n in (2, 3, 4, 7, 10):
            for epoch in (0, 1, 5, 123):
                m = shard_map(keys, n, epoch)
                count = {k: 0 for k in keys}
                for shard in m.values():
                    for k in shard:
                        count[k] += 1
                assert all(c == shard_coverage(n)
                           for c in count.values()), (n, epoch, count)

    def test_deterministic_across_validators_and_rejoin(self):
        # pure function of public inputs: a validator that crashes and
        # rejoins mid-round re-derives exactly the same shard
        keys = [f"/l{j}" for j in range(7)]
        for v in range(4):
            a = leaf_shard(keys, v, 4, epoch=9)
            b = leaf_shard(list(keys), v, 4, epoch=9)
            assert a == b
        # and distinct epochs rotate the load (not all identical)
        shards = {e: leaf_shard(keys, 0, 4, e) for e in range(4)}
        assert len({tuple(s) for s in shards.values()}) > 1

    def test_wrong_leaf_always_has_f_plus_1_honest_coverers(self):
        # the collusion argument the drill rests on, stated as a law:
        # for ANY leaf and ANY choice of f colluders, >= f+1 honest
        # validators cover it
        keys = [f"/l{j}" for j in range(5)]
        for n in (4, 7, 10):
            f = bft_fault_tolerance(n)
            for j in range(len(keys)):
                owners = leaf_owners(j, n, epoch=3)
                assert len(owners) - f >= f + 1, (n, j, owners)

    def test_single_validator_gets_everything(self):
        keys = ["/a", "/b"]
        assert leaf_shard(keys, 0, 1, 0) == keys


# ----------------------------------------------------- mode resolution
class TestModeResolution:
    def test_env_modes(self, monkeypatch):
        monkeypatch.delenv("BFLC_REDERIVE", raising=False)
        monkeypatch.delenv("BFLC_REDERIVE_LEGACY", raising=False)
        assert rederive_mode() == "off" and not rederive_armed()
        for m in REDERIVE_MODES:
            monkeypatch.setenv("BFLC_REDERIVE", m)
            assert rederive_mode() == m
        monkeypatch.setenv("BFLC_REDERIVE", "bogus")
        assert rederive_mode() == "off"
        monkeypatch.setenv("BFLC_REDERIVE", "full")
        monkeypatch.setenv("BFLC_REDERIVE_LEGACY", "1")
        assert rederive_mode() == "off"


# ------------------------------------------------------- the drills
class TestLyingWriterDrill:
    def test_sync_lie_fails_even_with_colluding_validator(self,
                                                          monkeypatch):
        """The acceptance drill: a corrupted (self-consistent) commit
        under --rederive shard fails certification; validator 0
        colludes (plane off — it signs anything) and cannot save it."""
        monkeypatch.setenv("BFLC_REDERIVE", "shard")
        fleet = _Fleet(["off", "shard", "shard", "shard"])
        try:
            fleet.register_all()
            with mock.patch.object(ls, "pack_entries",
                                   _corrupting_pack):
                last = fleet.drive_round(0)
            assert last["status"] == "CERT_TIMEOUT", last
            # the commit op never certified: the watermark stopped
            # below the writer's local chain tip
            info = fleet.client.request("info")
            assert info["certified_size"] < info["log_size"]
            # >= f+1 honest validators refused (coverage 2f+1 minus at
            # most f colluders) — quorum 3 of 4 is unreachable
            refusals = sum(s["refused"] for s in fleet.honest_stats())
            assert refusals >= bft_fault_tolerance(N_VALIDATORS) + 1
        finally:
            fleet.close()

    def test_async_drain_lie_fails_certification(self, monkeypatch):
        """The async half: a corrupted FedBuff drain commit (opcode 12)
        is refused — staleness weights re-derived from the certified
        stamps, not trusted."""
        monkeypatch.setenv("BFLC_REDERIVE", "shard")
        import dataclasses
        acfg = dataclasses.replace(CFG, async_buffer=3,
                                   max_staleness=5).validate()
        fleet = _Fleet(["shard"] * 4, cfg=acfg, seed=b"rd-async")
        try:
            fleet.register_all()
            last = None
            with mock.patch.object(ls, "pack_entries",
                                   _corrupting_pack):
                for i, w in enumerate(fleet.wallets[:3]):
                    blob = pack_pytree(_delta_tree(0.1 * (i + 1)))
                    d = hashlib.sha256(blob).digest()
                    payload = d + struct.pack("<qd", 10 + i, 1.0)
                    last = fleet.client.request(
                        "aupload", addr=w.address, blob=blob,
                        hash=d.hex(), n=10 + i, cost=1.0, base_epoch=0,
                        tag=_sign(w, "aupload", 0, payload))
            # the K-th admission triggered the drain inside its own
            # ack: the corrupted acommit cannot certify
            assert last["status"] == "CERT_TIMEOUT", last
            refusals = sum(s["refused"] for s in fleet.honest_stats())
            assert refusals >= 2
        finally:
            fleet.close()

    def test_honest_golden_pin_armed_vs_legacy(self, monkeypatch):
        """Byte-identical committed hashes armed vs the legacy pin, and
        the armed leg actually re-derived (no silent skips)."""
        monkeypatch.setenv("BFLC_REDERIVE", "shard")
        monkeypatch.delenv("BFLC_REDERIVE_LEGACY", raising=False)
        armed = _Fleet(["shard"] * 4, seed=b"rd-gold")
        try:
            armed.register_all()
            for ep in range(2):
                last = armed.drive_round(ep)
                assert last["ok"], last
            armed_hash = armed.model_hash()
            for s in armed.honest_stats():
                assert s["ok"] == 2, s
                assert s["refused"] == 0 and s["skipped"] == 0, s
        finally:
            armed.close()
        monkeypatch.setenv("BFLC_REDERIVE_LEGACY", "1")
        legacy = _Fleet(["shard"] * 4, seed=b"rd-gold")
        try:
            legacy.register_all()
            for ep in range(2):
                last = legacy.drive_round(ep)
                assert last["ok"], last
            assert legacy.model_hash() == armed_hash
            # the pin really turned the plane off everywhere
            assert all(v._rederiver is None for v in legacy.nodes)
        finally:
            legacy.close()

    def test_poisoned_nan_delta_refused_when_armed(self, monkeypatch):
        """Health-enforcement half: a NaN delta with a winning score
        merges into a byte-exact NaN model — certifies under legacy,
        REFUSED when armed."""
        def nan_delta(i):
            t = _delta_tree(0.1 * (i + 1))
            if i == 0:
                t["W"] = t["W"].copy()
                t["W"][0, 0] = np.float32("nan")
            return t

        def winning_scores(_j):
            return [1.0, 0.5, 0.4]      # slot 0 (the NaN) selected

        monkeypatch.setenv("BFLC_REDERIVE_LEGACY", "1")
        legacy = _Fleet(["shard"] * 4, seed=b"rd-nan")
        try:
            legacy.register_all()
            last = legacy.drive_round(0, delta_of=nan_delta,
                                      scores_of=winning_scores)
            assert last["ok"], last     # today: garbage certifies
            assert legacy.client.request("info")["epoch"] == 1
        finally:
            legacy.close()
        monkeypatch.delenv("BFLC_REDERIVE_LEGACY", raising=False)
        monkeypatch.setenv("BFLC_REDERIVE", "shard")
        armed = _Fleet(["shard"] * 4, seed=b"rd-nan")
        try:
            armed.register_all()
            last = armed.drive_round(0, delta_of=nan_delta,
                                     scores_of=winning_scores)
            assert last["status"] == "CERT_TIMEOUT", last
            refusals = sum(s["refused"] for s in armed.honest_stats())
            assert refusals >= 2
        finally:
            armed.close()

    def test_unavailable_evidence_degrades_to_counted_skip(
            self, monkeypatch):
        """The chaos-leg contract: armed validators whose writer sends
        no evidence (a pre-plane writer / every serving replica dead)
        sign on guard-check with zero stalls — skip counted, flight
        WARN recorded, the round completes."""
        from bflc_demo_tpu.obs import flight as obs_flight
        monkeypatch.delenv("BFLC_REDERIVE", raising=False)  # writer OFF
        fleet = _Fleet(["shard"] * 4, seed=b"rd-degrade")
        was_enabled = obs_flight.FLIGHT.enabled
        obs_flight.FLIGHT.enabled = True
        try:
            fleet.register_all()
            t0 = time.monotonic()
            last = fleet.drive_round(0)
            wall = time.monotonic() - t0
            assert last["ok"], last
            assert fleet.client.request("info")["epoch"] == 1
            assert wall < 10.0          # zero stalls, no fetch timeout
            for s in fleet.honest_stats():
                assert s["skipped"] >= 1, s
                assert s["refused"] == 0, s
            warns = [e for e in list(obs_flight.FLIGHT._ring)
                     if e.get("name") == "rederive_skipped"]
            assert warns and warns[0].get("level") == "WARN"
        finally:
            obs_flight.FLIGHT.enabled = was_enabled
            fleet.close()


# ------------------------------------------------ validator-path algebra
class TestRederivePath:
    def test_writer_and_validator_paths_byte_identical(self):
        """tools/check_reduction_spec.py's rederive leg, tier-1 sized —
        randomized trees/weights/selections x dtype x density."""
        import sys
        sys.path.insert(0, "tools")
        from check_reduction_spec import run_rederive_differential
        out = run_rederive_differential(trials=4, seed=3, max_n=10)
        assert out["mismatches"] == [], out

    def test_derive_leaves_zero_substitution(self):
        """Unselected slots never need their blobs: zeros rows are
        byte-equivalent under the spec's masked +0.0 terms."""
        from bflc_demo_tpu.meshagg.engine import ENGINE
        from bflc_demo_tpu.rederive.core import derive_leaves
        rng = np.random.default_rng(5)
        g = {"/a": rng.standard_normal((4, 3)).astype(np.float32),
             "/b": rng.standard_normal((7,)).astype(np.float32)}
        flats = [{k: rng.standard_normal(np.asarray(v).shape)
                  .astype(np.float32) for k, v in g.items()}
                 for _ in range(5)]
        weights = [3.0, 5.0, 2.0, 9.0, 4.0]
        selected = [1, 3]
        want = ENGINE.aggregate_flat(g, flats, weights, selected, 0.1)
        masked = [f if i in selected else None
                  for i, f in enumerate(flats)]
        got = derive_leaves(g, masked, weights, selected, 0.1,
                            sorted(g.keys()))
        for k in g:
            assert np.asarray(got[k]).tobytes() == \
                np.asarray(want[k]).tobytes()


# ------------------------------------------------------ hier cell tier
class TestCellRederive:
    def _scenario(self, tamper=False, break_tag=False):
        from bflc_demo_tpu.hier.partial import (cell_evidence_digest,
                                                cell_partial,
                                                partial_blob)
        from bflc_demo_tpu.ledger.base import encode_upload_op
        from bflc_demo_tpu.rederive.core import Rederiver
        rng = np.random.default_rng(11)
        members = [Wallet.from_seed(b"cell-m|%d" % i) for i in range(3)]
        cepoch, cell_index = 2, 1
        listing, blobs, admitted = [], {}, []
        for i, w in enumerate(members):
            tree = {"W": rng.standard_normal((5, 2)).astype(np.float32),
                    "b": rng.standard_normal((2,)).astype(np.float32)}
            blob = pack_pytree(tree)
            h = hashlib.sha256(blob).digest()
            n, cost = 10 + i, 1.0 + 0.1 * i
            tag = _sign(w, "upload", cepoch,
                        h + struct.pack("<qd", n, cost))
            listing.append([w.address, h.hex(), n, cost, tag,
                            w.public_bytes.hex()])
            blobs[h.hex()] = blob
            admitted.append((w.address, unpack_pytree(blob), n, cost))
        medians = [0.9, 0.8, 0.7]
        selected = [0, 1, 2]
        digest = cell_evidence_digest(
            cepoch, cell_index,
            [(s, bytes.fromhex(h), n, c)
             for s, h, n, c, _t, _p in listing],
            medians, selected)
        partial, n_clients, cost = cell_partial(admitted)
        if tamper:
            partial = {k: v for k, v in partial.items()}
            k0 = sorted(partial)[0]
            partial[k0] = np.asarray(partial[k0]).copy()
            partial[k0].flat[0] += np.float32(1.0)
        pblob = partial_blob(partial, cell_index, n_clients, digest)
        agg = Wallet.from_seed(b"cell-agg-1")
        op = encode_upload_op(agg.address,
                              hashlib.sha256(pblob).digest(),
                              n_clients, cost, 7)
        ev = {"epoch": cepoch, "updates": listing, "medians": medians,
              "selected": selected, "read_ep": ["127.0.0.1", 1]}
        if break_tag:
            ev["updates"][1][4] = "00" * 64
            # re-bind the digest so ONLY the signature check can refuse
        auth = {"blob": pblob.hex(), "cell": ev}
        rd = Rederiver("shard", 0, 4,
                       CFG, cell_registry={agg.address: (cell_index, 8)})

        class _Stub:
            cache = None

            def fetch(self, hashes, rs, co):
                return {h: blobs[h] for h in hashes}

            def close(self):
                pass

        rd.fetcher = _Stub()
        return rd, op, auth

    def test_honest_cell_partial_passes(self):
        rd, op, auth = self._scenario()
        assert rd.check_cell(op, auth) == ""
        assert rd.stats["cell_ok"] == 1

    def test_fabricated_partial_refused(self):
        rd, op, auth = self._scenario(tamper=True)
        err = rd.check_cell(op, auth)
        assert "not the deterministic FedAvg" in err

    def test_unverifiable_member_tag_refused(self):
        rd, op, auth = self._scenario(break_tag=True)
        err = rd.check_cell(op, auth)
        assert "tag unverifiable" in err

    def test_missing_evidence_is_counted_skip(self):
        rd, op, auth = self._scenario()
        assert rd.check_cell(op, {"blob": auth["blob"]}) == ""
        assert rd.stats["cell_skipped"] == 1

    def test_evidence_digest_binding(self):
        # a listing that does not hash to the certified #cellmeta
        # digest is refused (the aggregator committed to ONE story)
        rd, op, auth = self._scenario()
        auth["cell"]["medians"] = [0.1, 0.1, 0.1]
        err = rd.check_cell(op, auth)
        assert "#cellmeta digest" in err


class TestProcessFleetE2E:
    def test_armed_fleet_trains_and_validators_rederive(self, tmp_path):
        """The real deployment shape: OS-process clients + standby +
        4 validators with --rederive shard — training proceeds, and
        the fleet scrapes prove every validator actually re-derived
        (rederive_seconds counts > 0, shard coverage gauge = 2f+1)."""
        import json

        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        from bflc_demo_tpu.data import iid_shards, load_occupancy

        cfg = ProtocolConfig(client_num=4, comm_count=2,
                             aggregate_count=2, needed_update_count=2,
                             learning_rate=0.05, batch_size=32,
                             local_epochs=2).validate()
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(np.asarray(xtr), np.asarray(ytr),
                            cfg.client_num)
        tdir = str(tmp_path / "telemetry")
        res = run_federated_processes(
            "make_softmax_regression", shards,
            (np.asarray(xte), np.asarray(yte)), cfg, rounds=2,
            bft_validators=4, standbys=1, rederive="shard",
            telemetry_dir=tdir, timeout_s=240, verbose=False)
        assert (res.final_info or {}).get("epoch", 0) >= 2
        assert res.final_accuracy > 0.5
        # scrape evidence: each validator re-derived at least one
        # commit, at the expected 2f+1 shard coverage, refusing none
        derived, coverage, refusals = {}, set(), 0.0
        with open(f"{tdir}/metrics.jsonl") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                for role, snap in (rec.get("roles") or {}).items():
                    if not role.startswith("validator"):
                        continue
                    mm = (snap.get("snapshot") or snap).get(
                        "metrics") or {}
                    for s in mm.get("rederive_seconds",
                                    {}).get("samples", []):
                        derived[role] = max(derived.get(role, 0),
                                            s.get("count", 0))
                    for s in mm.get("rederive_shard_coverage",
                                    {}).get("samples", []):
                        coverage.add(s.get("value"))
                    for s in mm.get("rederive_refusals_total",
                                    {}).get("samples", []):
                        refusals = max(refusals, s.get("value", 0))
        assert len(derived) == 4 and all(c >= 1
                                         for c in derived.values()), \
            derived
        assert coverage == {shard_coverage(4)}, coverage
        assert refusals == 0


class TestQuorumArithmetic:
    def test_refusals_beat_quorum_at_reference_geometry(self):
        # the numbers behind the drill, pinned: n=4, f=1, quorum=3,
        # coverage 3 — any wrong leaf loses >= 2 honest votes, leaving
        # at most 2 signers
        n = N_VALIDATORS
        f = bft_fault_tolerance(n)
        q = bft_quorum(n)
        c = shard_coverage(n)
        assert (c - f) >= f + 1
        assert n - (c - f) < q
