"""Wire framing: binary blob frames, mixed-version interop, frame caps,
and chaos injection on the binary path (PR 3).

The binary variant ([len][\\x00BIN1][hlen][JSON header][raw tail]) must be
bit-faithful, coexist with legacy hex-JSON frames ON THE SAME SOCKET (a
mixed-version peer can switch formats frame by frame), die loudly on any
corrupt or overclaiming length field under the existing 256 MiB cap, and
remain fully visible to the chaos FaultInjector — a fault campaign that
silently skipped the fattest frames would be theater.
"""

import json
import socket
import struct
import threading
import time

import pytest

from bflc_demo_tpu.chaos.hooks import FaultInjector
from bflc_demo_tpu.comm import wire
from bflc_demo_tpu.comm.wire import (MAX_FRAME, WireError, blob_bytes,
                                     recv_msg, send_msg)
from bflc_demo_tpu.obs import trace as obs_trace


@pytest.fixture
def pair():
    a, b = socket.socketpair()
    yield a, b
    a.close()
    b.close()


@pytest.fixture
def armed_trace():
    """Arm the process-global span recorder for the test, restore after
    (no flush path: context/propagation only)."""
    t = obs_trace.TRACE
    saved = (t.enabled, t.sample, t.role)
    t.enabled, t.sample, t.role = True, 1.0, "test"
    try:
        yield t
    finally:
        t.enabled, t.sample, t.role = saved
        t._ring.clear()
        t._local = threading.local()


class TestBinaryFrames:
    def test_bytes_fields_round_trip_bit_exact(self, pair):
        a, b = pair
        blob = bytes(range(256)) * 17
        send_msg(a, {"method": "upload", "blob": blob, "hash": "ab" * 32,
                     "n": 7, "cost": 1.5})
        m = recv_msg(b)
        assert m == {"method": "upload", "blob": blob, "hash": "ab" * 32,
                     "n": 7, "cost": 1.5}
        assert isinstance(m["blob"], bytes)

    def test_multiple_binary_fields_keep_order_and_length(self, pair):
        a, b = pair
        send_msg(a, {"method": "stage", "x": b"\x00" * 100, "y": b"\x01",
                     "tag": "cafe"})
        m = recv_msg(b)
        assert m["x"] == b"\x00" * 100 and m["y"] == b"\x01"
        assert m["tag"] == "cafe"

    def test_empty_bytes_field(self, pair):
        a, b = pair
        send_msg(a, {"method": "m", "blob": b""})
        assert recv_msg(b)["blob"] == b""

    def test_wire_is_half_the_hex_size(self, pair):
        """The point of the exercise: no 2x hex inflation on blobs."""
        a, b = pair
        blob = b"\xab" * 50_000
        send_msg(a, {"blob": blob})
        m = recv_msg(b)
        assert m["blob"] == blob
        # a hex-JSON frame for the same blob is ~2x the bytes
        legacy = len(json.dumps({"blob": blob.hex()}).encode())
        binary = len(wire._encode({"blob": blob}))
        assert binary < legacy * 0.55

    def test_blob_bytes_accepts_both_representations(self):
        assert blob_bytes(b"\xde\xad") == b"\xde\xad"
        assert blob_bytes(bytearray(b"\x01")) == b"\x01"
        assert blob_bytes("dead") == b"\xde\xad"
        with pytest.raises(ValueError):
            blob_bytes("zz")            # not hex
        with pytest.raises(ValueError):
            blob_bytes(17)              # not a wire blob at all


class TestCompressedFrames:
    """The \\x00ZIP1 variant (data-plane PR): negotiated per-frame, size-
    thresholded, win-gated, bounded against deflate bombs, and fully
    interoperable with BIN1 and legacy hex-JSON on one socket."""

    def test_compressible_blob_rides_zip_and_roundtrips(self, pair):
        a, b = pair
        blob = bytes(range(256)) * 400          # 100 KB, compressible
        body = wire._maybe_compress(wire._encode({"blob": blob}))
        assert body[:5] in (wire._ZLIB_MAGIC, wire._ZSTD_MAGIC)
        send_msg(a, {"method": "m", "blob": blob})
        m = recv_msg(b)
        assert m["blob"] == blob and isinstance(m["blob"], bytes)

    def test_small_and_incompressible_frames_stay_raw(self):
        import os as _os
        assert wire._maybe_compress(wire._encode({"x": 1}))[:1] == b"{"
        rnd = _os.urandom(64 * 1024)            # deflate cannot win
        body = wire._maybe_compress(wire._encode({"blob": rnd}))
        assert body[:5] == wire._BIN_MAGIC

    def test_three_frame_generations_interleave_on_one_socket(
            self, pair, monkeypatch):
        """Acceptance pin: compressed, BIN1 and legacy hex-JSON frames
        interleaved on ONE socket all decode to the same content."""
        a, b = pair
        blob = b"\x42" * 20_000
        send_msg(a, {"method": "m", "blob": blob})      # compressed
        monkeypatch.setattr(wire, "_NO_COMPRESS", True)
        send_msg(a, {"method": "m", "blob": blob})      # BIN1
        monkeypatch.setattr(wire, "_NO_COMPRESS", False)
        legacy = json.dumps({"method": "m", "blob": blob.hex()},
                            separators=(",", ":")).encode()
        a.sendall(struct.pack(">I", len(legacy)) + legacy)  # hex-JSON
        send_msg(a, {"method": "m", "blob": blob})      # compressed
        frames = [recv_msg(b) for _ in range(4)]
        assert all(blob_bytes(m["blob"]) == blob for m in frames)
        assert isinstance(frames[2]["blob"], str)       # really legacy

    def test_claimed_raw_length_over_cap_rejected(self, pair):
        import zlib
        a, b = pair
        body = (wire._ZLIB_MAGIC + struct.pack(">I", MAX_FRAME + 1)
                + zlib.compress(b"x"))
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="outside"):
            recv_msg(b)

    def test_claimed_raw_length_zero_rejected(self, pair):
        """raw_len == 0 would make zlib's max_length / zstd's
        max_output_size mean UNBOUNDED — the deflate-bomb hole; it must
        die at the header check, before any inflation."""
        import zlib
        a, b = pair
        body = (wire._ZLIB_MAGIC + struct.pack(">I", 0)
                + zlib.compress(b"A" * 100_000))
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="outside"):
            recv_msg(b)

    def test_corrupt_zip_payload_rejected(self, pair):
        a, b = pair
        body = wire._ZLIB_MAGIC + struct.pack(">I", 10) + b"garbage!"
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="undecodable compressed"):
            recv_msg(b)

    def test_inflated_length_mismatch_rejected(self, pair):
        import zlib
        a, b = pair
        body = (wire._ZLIB_MAGIC + struct.pack(">I", 10)
                + zlib.compress(b"abc"))        # claims 10, inflates 3
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="inflated|overruns"):
            recv_msg(b)

    def test_truncated_zip_header_rejected(self, pair):
        a, b = pair
        body = wire._ZLIB_MAGIC + b"\x00"
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="truncated"):
            recv_msg(b)

    def test_data_plane_legacy_switch_pins_compression_off(
            self, monkeypatch):
        monkeypatch.setattr(wire, "_NO_COMPRESS", True)
        blob = b"\x00" * 50_000
        body = wire._maybe_compress(wire._encode({"blob": blob}))
        assert body[:5] == wire._BIN_MAGIC      # raw BIN1, not zip

    def test_chaos_drop_fires_on_compressed_send(self, pair,
                                                 monkeypatch):
        from bflc_demo_tpu.chaos.hooks import FaultInjector
        a, b = pair
        inj = FaultInjector({
            "t0": time.time() - 1.0, "role": "test", "seed": 1,
            "windows": [{"start": 0.0, "end": 3600.0, "mode": "drop",
                         "ports": [], "p": 1.0}]})
        monkeypatch.setattr(wire, "_INJECTOR", inj)
        with pytest.raises(WireError, match="dropped"):
            send_msg(a, {"method": "m", "blob": b"\x01" * 20_000})
        assert inj.injected["drop"] == 1


class TestMixedVersionPeers:
    def test_old_and_new_frames_interleave_on_one_socket(self, pair):
        """A legacy peer (hex-in-JSON) and a binary-frame peer can share
        one connection: the receiver keys off each frame's first byte."""
        a, b = pair
        blob = b"\x10\x20\x30"
        # new-format frame
        send_msg(a, {"method": "upload", "blob": blob})
        # legacy frame, hand-built exactly as the old send_msg did
        legacy_body = json.dumps(
            {"method": "upload", "blob": blob.hex()},
            separators=(",", ":")).encode()
        a.sendall(struct.pack(">I", len(legacy_body)) + legacy_body)
        # another new-format frame
        send_msg(a, {"method": "done", "blob": blob})

        m1, m2, m3 = recv_msg(b), recv_msg(b), recv_msg(b)
        assert blob_bytes(m1["blob"]) == blob
        assert blob_bytes(m2["blob"]) == blob     # hex str, same bytes
        assert isinstance(m2["blob"], str)
        assert blob_bytes(m3["blob"]) == blob

    def test_legacy_switch_forces_hex_json(self, pair, monkeypatch):
        """BFLC_CONTROL_PLANE_LEGACY pins the old format — the benchmark
        baseline leg — and the result is decodable by any peer."""
        a, b = pair
        monkeypatch.setattr(wire, "_JSON_ONLY", True)
        send_msg(a, {"method": "m", "blob": b"\x05\x06"})
        m = recv_msg(b)
        assert m["blob"] == "0506"


class TestFrameCaps:
    def test_oversized_length_prefix_rejected(self, pair):
        a, b = pair
        a.sendall(struct.pack(">I", MAX_FRAME + 1))
        with pytest.raises(WireError, match="exceeds cap"):
            recv_msg(b)

    def test_binary_header_length_overrun_rejected(self, pair):
        a, b = pair
        body = wire._BIN_MAGIC + struct.pack(">I", 10_000) + b"{}"
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="header length"):
            recv_msg(b)

    def test_binary_manifest_overrun_rejected(self, pair):
        """A manifest claiming more tail bytes than the frame holds must
        be a WireError, never an overread or a giant allocation."""
        a, b = pair
        hdr = json.dumps({"m": 1, "_bin": [["blob", 1 << 30]]}).encode()
        body = (wire._BIN_MAGIC + struct.pack(">I", len(hdr)) + hdr
                + b"xy")
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="overruns"):
            recv_msg(b)

    def test_binary_trailing_garbage_rejected(self, pair):
        a, b = pair
        hdr = json.dumps({"m": 1, "_bin": [["blob", 1]]}).encode()
        body = (wire._BIN_MAGIC + struct.pack(">I", len(hdr)) + hdr
                + b"abc")             # manifest consumes 1 of 3 bytes
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="trailing"):
            recv_msg(b)

    def test_truncated_binary_header_rejected(self, pair):
        a, b = pair
        body = wire._BIN_MAGIC + b"\x00"
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="truncated"):
            recv_msg(b)

    def test_negative_manifest_length_rejected(self, pair):
        a, b = pair
        hdr = json.dumps({"_bin": [["blob", -5]]}).encode()
        body = wire._BIN_MAGIC + struct.pack(">I", len(hdr)) + hdr
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(WireError, match="overruns"):
            recv_msg(b)


class TestTraceparentOnWire:
    """Causal trace context (obs.trace): while a sampled span is active,
    every frame carries `_tp` — through the BIN1, legacy hex-JSON and
    compressed variants unchanged — and an untraced peer just sees one
    extra JSON key.  Sampling off ⇒ not a byte on the wire."""

    def test_tp_rides_binary_frames(self, pair, armed_trace):
        a, b = pair
        blob = b"\xab" * 2000
        with armed_trace.start_trace("root"):
            tp = armed_trace.current_traceparent()
            send_msg(a, {"method": "upload", "blob": blob})
        m = recv_msg(b)
        assert m["_tp"] == tp and m["blob"] == blob

    def test_tp_rides_legacy_hex_json_frames(self, pair, armed_trace,
                                             monkeypatch):
        a, b = pair
        monkeypatch.setattr(wire, "_JSON_ONLY", True)
        with armed_trace.start_trace("root"):
            tp = armed_trace.current_traceparent()
            send_msg(a, {"method": "upload", "blob": b"\x05\x06"})
        m = recv_msg(b)
        assert m["_tp"] == tp
        assert m["blob"] == "0506"      # really the legacy encoding

    def test_tp_rides_compressed_frames(self, pair, armed_trace):
        a, b = pair
        blob = bytes(range(256)) * 400          # compressible
        with armed_trace.start_trace("root"):
            tp = armed_trace.current_traceparent()
            body = wire._maybe_compress(wire._encode(
                {"blob": blob, "_tp": tp}))
            assert body[:5] in (wire._ZLIB_MAGIC, wire._ZSTD_MAGIC)
            send_msg(a, {"method": "m", "blob": blob})
        m = recv_msg(b)
        assert m["_tp"] == tp and m["blob"] == blob

    def test_untraced_peer_ignores_the_extra_key(self, pair,
                                                 armed_trace):
        """A traced frame against a peer that knows nothing about
        tracing: the read dispatch answers normally (the `_tp` key is
        inert data)."""
        from bflc_demo_tpu.comm.dataplane import handle_read
        a, b = pair
        with armed_trace.start_trace("root"):
            send_msg(a, {"method": "model", "meta": 1})
        m = recv_msg(b)
        assert "_tp" in m
        r = handle_read(m["method"], m,
                        blob_lookup=lambda d: None,
                        model_state=lambda: (3, b"\0" * 32, b"x"))
        assert r == {"ok": True, "epoch": 3, "hash": "00" * 32}

    def test_no_tp_bytes_when_sampling_off(self, pair):
        """The zero-overhead-off contract at the wire: the default
        (disabled) recorder adds nothing — the encoded frame is
        byte-identical to an untraced sender's."""
        a, b = pair
        assert not obs_trace.TRACE.enabled
        with obs_trace.TRACE.start_trace("root"):
            send_msg(a, {"method": "m", "x": 1})
        m = recv_msg(b)
        assert "_tp" not in m
        assert wire._encode({"method": "m", "x": 1}) == \
            json.dumps({"method": "m", "x": 1},
                       separators=(",", ":")).encode()

    def test_chaos_drop_still_fires_on_traced_frames(self, pair,
                                                     armed_trace,
                                                     monkeypatch):
        a, b = pair
        inj = FaultInjector({
            "t0": time.time() - 1.0, "role": "test", "seed": 1,
            "windows": [{"start": 0.0, "end": 3600.0, "mode": "drop",
                         "ports": [], "p": 1.0}]})
        monkeypatch.setattr(wire, "_INJECTOR", inj)
        with armed_trace.start_trace("root"):
            with pytest.raises(WireError, match="dropped"):
                send_msg(a, {"method": "m", "blob": b"\x01" * 1000})
        assert inj.injected["drop"] == 1

    def test_chaos_delay_still_fires_on_traced_frames(self, pair,
                                                      armed_trace,
                                                      monkeypatch):
        a, b = pair
        inj = FaultInjector({
            "t0": time.time() - 1.0, "role": "test", "seed": 1,
            "windows": [{"start": 0.0, "end": 3600.0, "mode": "delay",
                         "ports": [], "p": 1.0, "delay_ms": 30.0}]})
        monkeypatch.setattr(wire, "_INJECTOR", inj)
        t0 = time.perf_counter()
        with armed_trace.start_trace("root"):
            tp = armed_trace.current_traceparent()
            send_msg(a, {"method": "m", "blob": b"\x03" * 10})
        assert time.perf_counter() - t0 >= 0.025
        monkeypatch.setattr(wire, "_INJECTOR", None)
        m = recv_msg(b)
        assert m["_tp"] == tp and m["blob"] == b"\x03" * 10


class TestChaosOnBinaryFrames:
    """The FaultInjector hook must keep firing on the new path: the
    fattest frames (blob mirroring, model fetch) are exactly the ones a
    fault campaign most needs to partition/drop."""

    def _injector(self, mode, p=1.0, **kw):
        now = time.time()
        return FaultInjector({
            "t0": now - 1.0, "role": "test", "seed": 1,
            "windows": [{"start": 0.0, "end": 3600.0, "mode": mode,
                         "ports": [], "p": p, **kw}]})

    def test_drop_fires_on_binary_send(self, pair, monkeypatch):
        a, b = pair
        inj = self._injector("drop", p=1.0)
        monkeypatch.setattr(wire, "_INJECTOR", inj)
        with pytest.raises(WireError, match="dropped"):
            send_msg(a, {"method": "upload", "blob": b"\x01" * 1000})
        assert inj.injected["drop"] == 1

    def test_partition_fires_on_binary_recv(self, pair, monkeypatch):
        a, b = pair
        monkeypatch.setattr(wire, "_INJECTOR", None)
        send_msg(a, {"method": "m", "blob": b"\x02"})
        inj = self._injector("partition")
        monkeypatch.setattr(wire, "_INJECTOR", inj)
        with pytest.raises(WireError, match="partitioned"):
            recv_msg(b)
        assert inj.injected["partition"] == 1

    def test_delay_fires_on_binary_send(self, pair, monkeypatch):
        a, b = pair
        inj = self._injector("delay", p=1.0, delay_ms=30.0)
        monkeypatch.setattr(wire, "_INJECTOR", inj)
        t0 = time.perf_counter()
        send_msg(a, {"method": "m", "blob": b"\x03" * 10})
        assert time.perf_counter() - t0 >= 0.025
        assert inj.injected["delay"] == 1
        monkeypatch.setattr(wire, "_INJECTOR", None)
        assert recv_msg(b)["blob"] == b"\x03" * 10
