"""Protocol genome tests — the constants the reference duplicates unchecked."""

import pytest

from bflc_demo_tpu.protocol import DEFAULT_PROTOCOL, ProtocolConfig


def test_reference_parity_constants():
    # SURVEY.md §2d — CommitteePrecompiled.h:7-19 and main.py:52-88
    p = DEFAULT_PROTOCOL
    assert p.client_num == 20
    assert p.comm_count == 4
    assert p.aggregate_count == 6
    assert p.needed_update_count == 10
    assert p.learning_rate == 0.001
    assert p.batch_size == 100
    assert p.max_epoch == 1000
    assert p.genesis_epoch == -999
    assert p.initial_trained_epoch == -1
    assert p.trainer_count == 16


@pytest.mark.parametrize("kw", [
    dict(comm_count=0),
    dict(comm_count=20),
    dict(aggregate_count=11),
    dict(aggregate_count=0),
    dict(needed_update_count=17),  # > client_num - comm_count
    dict(learning_rate=0.0),
    dict(batch_size=0),
])
def test_invalid_configs_rejected(kw):
    with pytest.raises(ValueError):
        ProtocolConfig(**kw).validate()
