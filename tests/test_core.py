"""Unit tests for the core FL math against hand-computed values and the
reference semantics documented in SURVEY.md §3.2-3.4."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bflc_demo_tpu.core import (
    softmax_cross_entropy, accuracy, local_train, evaluate, score_candidates,
    median_scores, rank_desc_stable, topk_selection_mask, aggregate,
    elect_committee,
)
from bflc_demo_tpu.models import make_softmax_regression


MODEL = make_softmax_regression()


def _rand_batch(rng, n=100):
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = np.zeros((n, 2), np.float32)
    y[np.arange(n), rng.integers(0, 2, n)] = 1.0
    return jnp.asarray(x), jnp.asarray(y)


class TestLosses:
    def test_ce_uniform_logits(self):
        logits = jnp.zeros((4, 2))
        y = jnp.eye(2)[jnp.array([0, 1, 0, 1])]
        np.testing.assert_allclose(softmax_cross_entropy(logits, y),
                                   np.log(2.0), rtol=1e-6)

    def test_accuracy(self):
        logits = jnp.array([[2.0, 1.0], [0.0, 3.0], [5.0, 0.0]])
        y = jnp.eye(2)[jnp.array([0, 1, 1])]
        np.testing.assert_allclose(accuracy(logits, y), 2.0 / 3.0, rtol=1e-6)


class TestLocalTrain:
    def test_delta_encodes_sgd_path(self):
        """delta == (params_in - params_out)/lr exactly (main.py:153-155)."""
        rng = np.random.default_rng(0)
        x, y = _rand_batch(rng, 300)
        params = MODEL.init_params()
        delta, cost = local_train(MODEL.apply, params, x, y,
                                  lr=0.001, batch_size=100)
        # recompute by hand: 3 plain SGD steps
        p = params
        costs = []
        for b in range(3):
            bx, by = x[b * 100:(b + 1) * 100], y[b * 100:(b + 1) * 100]
            c, g = jax.value_and_grad(
                lambda q: softmax_cross_entropy(MODEL.apply(q, bx), by))(p), None
            cost_b, grads = c[0], jax.grad(
                lambda q: softmax_cross_entropy(MODEL.apply(q, bx), by))(p)
            costs.append(cost_b)
            p = jax.tree_util.tree_map(lambda w, gw: w - 0.001 * gw, p, grads)
        expect_delta = jax.tree_util.tree_map(
            lambda a, b_: (a - b_) / 0.001, params, p)
        for k in ("W", "b"):
            np.testing.assert_allclose(delta[k], expect_delta[k],
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(cost, np.mean(costs), rtol=1e-5)

    def test_remainder_dropped(self):
        """floor(n/bs) batches, remainder unused (main.py:140)."""
        rng = np.random.default_rng(1)
        x, y = _rand_batch(rng, 305)
        params = MODEL.init_params()
        d305, _ = local_train(MODEL.apply, params, x, y, lr=0.001, batch_size=100)
        d300, _ = local_train(MODEL.apply, params, x[:300], y[:300],
                              lr=0.001, batch_size=100)
        np.testing.assert_allclose(d305["W"], d300["W"], rtol=1e-6)

    def test_training_reduces_loss(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((400, 5)).astype(np.float32)
        w_true = rng.standard_normal((5, 2)).astype(np.float32)
        y_id = np.argmax(x @ w_true, axis=1)
        y = jnp.eye(2)[y_id]
        x = jnp.asarray(x)
        params = MODEL.init_params()
        before = softmax_cross_entropy(MODEL.apply(params, x), y)
        delta, _ = local_train(MODEL.apply, params, x, y, lr=0.05,
                               batch_size=100, local_epochs=20)
        trained = jax.tree_util.tree_map(lambda p, d: p - 0.05 * d,
                                         params, delta)
        after = softmax_cross_entropy(MODEL.apply(trained, x), y)
        assert float(after) < float(before)
        assert float(evaluate(MODEL.apply, trained, x, y)) > 0.8


class TestScoring:
    def test_matches_sequential_eval(self):
        """vmap-batched scoring == per-candidate loop (main.py:212-217)."""
        rng = np.random.default_rng(3)
        x, y = _rand_batch(rng, 200)
        params = MODEL.init_params(1)
        k = 10
        deltas = {
            "W": jnp.asarray(rng.standard_normal((k, 5, 2)), jnp.float32),
            "b": jnp.asarray(rng.standard_normal((k, 2)), jnp.float32),
        }
        scores = score_candidates(MODEL.apply, params, deltas, 0.001, x, y)
        assert scores.shape == (k,)
        for i in range(k):
            cand = jax.tree_util.tree_map(
                lambda g, d: g - 0.001 * d[i], params, deltas)
            np.testing.assert_allclose(
                scores[i], evaluate(MODEL.apply, cand, x, y), rtol=1e-6)


class TestMedianRank:
    def test_median_odd_even(self):
        m = jnp.array([[1.0, 5.0], [3.0, 1.0], [2.0, 3.0], [10.0, 7.0]])
        mask = jnp.array([True, True, True, False])
        np.testing.assert_allclose(median_scores(m, mask), [2.0, 3.0])
        mask4 = jnp.ones(4, bool)
        np.testing.assert_allclose(median_scores(m, mask4), [2.5, 4.0])

    def test_rank_stable_tiebreak(self):
        s = jnp.array([0.5, 0.9, 0.5, 0.1])
        v = jnp.ones(4, bool)
        np.testing.assert_array_equal(rank_desc_stable(s, v), [1, 0, 2, 3])

    def test_topk_mask_respects_validity(self):
        s = jnp.array([0.9, 0.8, 0.7, 0.6, 0.5])
        v = jnp.array([True, False, True, True, True])
        mask = topk_selection_mask(s, v, 3)
        np.testing.assert_array_equal(mask, [True, False, True, True, False])


class TestAggregate:
    def _setup(self, k=10, c=4, seed=4):
        rng = np.random.default_rng(seed)
        g = {"W": jnp.asarray(rng.standard_normal((5, 2)), jnp.float32),
             "b": jnp.asarray(rng.standard_normal((2,)), jnp.float32)}
        deltas = {
            "W": jnp.asarray(rng.standard_normal((k, 5, 2)), jnp.float32),
            "b": jnp.asarray(rng.standard_normal((k, 2)), jnp.float32)}
        n = jnp.asarray(rng.integers(100, 400, k), jnp.int32)
        costs = jnp.asarray(rng.random(k), jnp.float32)
        scores = jnp.asarray(rng.random((c, k)), jnp.float32)
        return g, deltas, n, costs, scores

    def test_weighted_fedavg_exact(self):
        """Reproduces .cpp:369-414 arithmetic by hand."""
        g, deltas, n, costs, scores = self._setup()
        res = aggregate(g, deltas, n, costs, scores,
                        jnp.ones(4, bool), jnp.ones(10, bool), 0.001, 6)
        med = np.median(np.asarray(scores), axis=0)
        top6 = np.argsort(-med, kind="stable")[:6]
        w = np.zeros(10); w[top6] = np.asarray(n)[top6]
        expect_W = np.asarray(g["W"]) - 0.001 * (
            np.tensordot(w, np.asarray(deltas["W"]), axes=1) / w.sum())
        np.testing.assert_allclose(res.params["W"], expect_W, rtol=1e-5)
        np.testing.assert_allclose(
            res.global_loss, np.asarray(costs)[top6].sum() / 6, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(res.selected)[top6],
                                      np.ones(6, bool))

    def test_fedavg_of_client_models_identity(self):
        """global -= lr*wmean(delta) == sample-weighted mean of client models
        when every client starts from global (SURVEY.md §2c DP row)."""
        g, _, n, costs, scores = self._setup()
        rng = np.random.default_rng(5)
        k = 10
        # client post-training models
        client_W = np.asarray(g["W"])[None] + rng.standard_normal(
            (k, 5, 2)).astype(np.float32)
        client_b = np.asarray(g["b"])[None] + rng.standard_normal(
            (k, 2)).astype(np.float32)
        deltas = {
            "W": jnp.asarray((np.asarray(g["W"])[None] - client_W) / 0.001),
            "b": jnp.asarray((np.asarray(g["b"])[None] - client_b) / 0.001)}
        res = aggregate(g, deltas, n, costs, scores,
                        jnp.ones(4, bool), jnp.ones(k, bool), 0.001, k)
        w = np.asarray(n, np.float32)
        expect = np.tensordot(w, client_W, axes=1) / w.sum()
        np.testing.assert_allclose(res.params["W"], expect, rtol=1e-4)

    def test_election_top4(self):
        g, deltas, n, costs, scores = self._setup()
        res = aggregate(g, deltas, n, costs, scores,
                        jnp.ones(4, bool), jnp.ones(10, bool), 0.001, 6)
        med = np.median(np.asarray(scores), axis=0)
        expect = np.argsort(-med, kind="stable")[:4]
        electees, emask = elect_committee(res.order, jnp.ones(10, bool), 4)
        np.testing.assert_array_equal(electees, expect)
        assert np.all(np.asarray(emask))

    def test_election_masks_invalid_slots(self):
        """Fewer valid updates than comm_count -> invalid electees flagged so
        a dead slot can never gain the committee role."""
        g, deltas, n, costs, scores = self._setup()
        valid = jnp.array([True, True, True] + [False] * 7)
        res = aggregate(g, deltas, n, costs, scores,
                        jnp.ones(4, bool), valid, 0.001, 6)
        electees, emask = elect_committee(res.order, valid, 4)
        assert np.asarray(emask).sum() == 3
        assert np.all(np.asarray(valid)[np.asarray(electees)[np.asarray(emask)]])

    def test_invalid_updates_excluded(self):
        g, deltas, n, costs, scores = self._setup()
        valid = jnp.array([True] * 5 + [False] * 5)
        res = aggregate(g, deltas, n, costs, scores,
                        jnp.ones(4, bool), valid, 0.001, 6)
        assert not np.any(np.asarray(res.selected)[5:])
        # only the 5 valid ones can be selected
        assert np.asarray(res.selected).sum() == 5


class TestJitStability:
    def test_aggregate_jit_cache(self):
        """Same static shapes -> no retrace (static-shape requirement)."""
        g, deltas, n, costs, scores = TestAggregate()._setup()
        r1 = aggregate(g, deltas, n, costs, scores, jnp.ones(4, bool),
                       jnp.ones(10, bool), 0.001, 6)
        r2 = aggregate(g, deltas, n, costs, scores, jnp.ones(4, bool),
                       jnp.ones(10, bool), 0.001, 6)
        np.testing.assert_allclose(r1.params["W"], r2.params["W"])
