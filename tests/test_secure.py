"""Secure-aggregation tests: exact mask cancellation, privacy of individual
contributions, and FedAvg equivalence up to fixed-point quantisation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.core import apply_selection
from bflc_demo_tpu.parallel import client_axis_mesh
from bflc_demo_tpu.parallel.secure import (secure_masked_sum, secure_fedavg,
                                           derive_pair_seeds,
                                           _client_mask, _client_mask_dh,
                                           _SCALE)


def _vals(rng, n=16, shape=(5, 2)):
    return {"W": jnp.asarray(rng.standard_normal((n,) + shape), jnp.float32),
            "b": jnp.asarray(rng.standard_normal((n, 2)), jnp.float32)}


class TestMaskCancellation:
    def test_pairwise_masks_cancel_exactly(self):
        key = jax.random.PRNGKey(0)
        n = 8
        total = jnp.zeros((4, 4), jnp.uint32)
        for i in range(n):
            total = total + _client_mask(key, jnp.int32(i), n, (4, 4), 0)
        np.testing.assert_array_equal(np.asarray(total), 0)

    def test_sum_matches_plain_sum(self):
        rng = np.random.default_rng(0)
        mesh = client_axis_mesh(8)
        vals = _vals(rng)
        got = secure_masked_sum(mesh, vals, jax.random.PRNGKey(1))
        for k in vals:
            want = np.asarray(vals[k]).sum(axis=0)
            np.testing.assert_allclose(np.asarray(got[k]), want,
                                       atol=2 * len(vals[k]) / _SCALE)

    def test_individual_contribution_is_blinded(self):
        """A single client's masked payload must look nothing like its
        plaintext: correlation with the true value ~ 0, bits ~ uniform."""
        key = jax.random.PRNGKey(2)
        rng = np.random.default_rng(3)
        x = rng.standard_normal((64, 64)).astype(np.float32)
        q = np.round(np.clip(x, -64, 64) * _SCALE).astype(np.int32)
        masked = np.asarray(
            q.astype(np.uint32) +
            np.asarray(_client_mask(key, jnp.int32(3), 16, (64, 64), 0)))
        # view masked words as signed and normalise; correlation with the
        # plaintext should be negligible
        m = masked.astype(np.int64)
        m = (m - m.mean()) / (m.std() + 1e-9)
        xn = (x - x.mean()) / x.std()
        corr = float(np.abs((m * xn).mean()))
        assert corr < 0.05, corr
        # top byte of the masked words ~ uniform (entropy check)
        top = (masked >> 24) & 0xFF
        counts = np.bincount(top.reshape(-1), minlength=256)
        assert counts.max() < 4 * counts.mean()

    def test_capacity_guard(self):
        """N*clip beyond int32 fixed-point capacity is rejected, not
        silently wrapped."""
        mesh = client_axis_mesh(8)
        rng = np.random.default_rng(9)
        vals = _vals(rng, n=16)
        with pytest.raises(ValueError):
            secure_masked_sum(mesh, vals, jax.random.PRNGKey(0),
                              clip=4096.0)     # 16 * 4096 = 65536 > 32768

    def test_different_rounds_different_masks(self):
        k = jax.random.PRNGKey(4)
        m1 = np.asarray(_client_mask(jax.random.fold_in(k, 1), jnp.int32(0),
                                     8, (16,), 0))
        m2 = np.asarray(_client_mask(jax.random.fold_in(k, 2), jnp.int32(0),
                                     8, (16,), 0))
        assert not np.array_equal(m1, m2)


class TestDHPairKeys:
    """The X25519 key-agreement mode: pair seeds come from per-pair DH, so
    the aggregator (holding no client private keys) cannot derive or strip
    any mask — closing the round-1 shared-round-key stub."""

    def _seeds(self, n=8, rnd=3):
        from bflc_demo_tpu.comm.identity import provision_wallets
        wallets, _ = provision_wallets(n, b"secure-dh-master-000001")
        return derive_pair_seeds(wallets, rnd)

    def test_dh_masks_cancel_exactly(self):
        n = 8
        seeds = self._seeds(n)
        total = jnp.zeros((4, 4), jnp.uint32)
        for i in range(n):
            total = total + _client_mask_dh(seeds, jnp.int32(i), n, (4, 4),
                                            0)
        np.testing.assert_array_equal(np.asarray(total), 0)

    def test_dh_sum_matches_plain_sum(self):
        rng = np.random.default_rng(21)
        mesh = client_axis_mesh(8)
        n = 8
        vals = _vals(rng, n)
        got = secure_masked_sum(mesh, vals, jax.random.PRNGKey(0),
                                pair_seeds=self._seeds(n))
        for k in vals:
            want = np.asarray(vals[k]).sum(axis=0)
            np.testing.assert_allclose(np.asarray(got[k]), want,
                                       atol=2 * n / _SCALE)

    def test_dh_rounds_and_pairs_differ(self):
        n = 8
        s3 = np.asarray(self._seeds(n, rnd=3))
        s4 = np.asarray(self._seeds(n, rnd=4))
        assert not np.array_equal(s3, s4)            # round-bound
        np.testing.assert_array_equal(s3, s3.transpose(1, 0, 2))  # symmetric
        iu = np.triu_indices(n, k=1)
        flat = s3[iu[0], iu[1]].reshape(-1, 8)
        assert len(np.unique(flat, axis=0)) == len(flat)   # distinct pairs

    def test_dh_secure_fedavg_matches_plain(self):
        rng = np.random.default_rng(22)
        mesh = client_axis_mesh(4)
        n = 8
        deltas = _vals(rng, n)
        params = {"W": jnp.asarray(rng.standard_normal((5, 2)), jnp.float32),
                  "b": jnp.asarray(rng.standard_normal((2,)), jnp.float32)}
        ns = jnp.asarray(rng.integers(100, 400, n), jnp.int32)
        sel = jnp.asarray(rng.random(n) < 0.5)
        got = secure_fedavg(mesh, deltas, ns, sel, params, 0.05,
                            jax.random.PRNGKey(0),
                            pair_seeds=self._seeds(n))
        want = apply_selection(params, deltas, ns, sel, 0.05)
        for k in params:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(want[k]),
                                       atol=0.05 * n / _SCALE + 1e-6)

    def test_same_shape_leaves_get_distinct_masks(self):
        """Regression: two same-shape leaves of one client's delta must be
        blinded with DIFFERENT mask bits — otherwise masked_A - masked_B
        leaks the client's exact cross-leaf difference (ResNet deltas
        repeat conv shapes many times)."""
        from bflc_demo_tpu.parallel.secure import (_client_mask,
                                                   _client_mask_dh)
        key = jax.random.PRNGKey(7)
        i = jnp.asarray(1)
        m0 = _client_mask(key, i, 4, (8,), leaf_idx=0)
        m1 = _client_mask(key, i, 4, (8,), leaf_idx=1)
        assert not np.array_equal(np.asarray(m0), np.asarray(m1))
        seeds = self._seeds(4)
        d0 = _client_mask_dh(seeds, i, 4, (8,), leaf_idx=0)
        d1 = _client_mask_dh(seeds, i, 4, (8,), leaf_idx=1)
        assert not np.array_equal(np.asarray(d0), np.asarray(d1))

    def test_bad_seed_shape_rejected(self):
        mesh = client_axis_mesh(4)
        vals = _vals(np.random.default_rng(0), 8)
        with pytest.raises(ValueError):
            secure_masked_sum(mesh, vals, jax.random.PRNGKey(0),
                              pair_seeds=jnp.zeros((4, 4, 2), jnp.uint32))


class TestSecureMeshRuntime:
    """secure_aggregation=True through the full protocol round program —
    the BASELINE config-4 capability, not just the shelf component."""

    def _run(self, secure, wallets=None, rounds=2):
        from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.models import make_softmax_regression
        from bflc_demo_tpu.protocol import ProtocolConfig

        cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                             needed_update_count=3, learning_rate=0.05,
                             batch_size=16, local_epochs=1)
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1200], ytr[:1200], 8)
        return run_federated_mesh(
            make_softmax_regression(), shards, (xte[:400], yte[:400]), cfg,
            rounds=rounds, seed=3, secure_aggregation=secure,
            secure_wallets=wallets)

    def test_secure_run_commits_plain_run_model(self):
        """The secure run's committed global model equals the plain run's
        within fixed-point quantisation tolerance, end-to-end (ledger audit
        included on both paths)."""
        plain = self._run(secure=False)
        masked = self._run(secure=True)
        for key in plain.final_params:
            np.testing.assert_allclose(
                np.asarray(masked.final_params[key]),
                np.asarray(plain.final_params[key]), atol=5e-3)
        assert masked.rounds_completed == plain.rounds_completed

    def test_secure_dh_run_with_wallets(self):
        """DH mode: per-pair X25519 mask keys, aggregator cannot strip."""
        from bflc_demo_tpu.comm.identity import provision_wallets

        wallets, _ = provision_wallets(8, b"mesh-secure-master-01")
        plain = self._run(secure=False)
        masked = self._run(secure=True, wallets=wallets)
        for key in plain.final_params:
            np.testing.assert_allclose(
                np.asarray(masked.final_params[key]),
                np.asarray(plain.final_params[key]), atol=5e-3)

    def test_secure_active_participation(self):
        """Sampled-participation slots: the mask cancellation spans exactly
        the round's k+c occupants."""
        from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh
        from bflc_demo_tpu.comm.identity import provision_wallets
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.models import make_softmax_regression
        from bflc_demo_tpu.protocol import ProtocolConfig

        cfg = ProtocolConfig(client_num=12, comm_count=2, aggregate_count=2,
                             needed_update_count=3, learning_rate=0.05,
                             batch_size=16, local_epochs=1)
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1200], ytr[:1200], 12)
        wallets, _ = provision_wallets(12, b"mesh-secure-master-02")
        res = run_federated_mesh(
            make_softmax_regression(), shards, (xte[:400], yte[:400]), cfg,
            rounds=2, seed=3, participation="active",
            secure_aggregation=True, secure_wallets=wallets)
        assert res.rounds_completed == 2
        assert all(np.isfinite(a) for _, a in res.accuracy_history)

    def test_secure_batched_shared_key_matches_plain(self):
        """rounds_per_dispatch > 1 with SHARED-KEY secure aggregation: one
        fresh host key per dispatch, re-keyed per round by folding the scan
        counter — the amortised path blinds its merges too."""
        from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.models import make_softmax_regression
        from bflc_demo_tpu.protocol import ProtocolConfig

        cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                             needed_update_count=3, learning_rate=0.05,
                             batch_size=16, local_epochs=1)
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1200], ytr[:1200], 8)

        def run(secure):
            return run_federated_mesh(
                make_softmax_regression(), shards, (xte[:400], yte[:400]),
                cfg, rounds=4, rounds_per_dispatch=2, seed=3,
                secure_aggregation=secure)

        plain = run(False)
        masked = run(True)
        assert masked.rounds_completed == 4
        for key in plain.final_params:
            np.testing.assert_allclose(
                np.asarray(masked.final_params[key]),
                np.asarray(plain.final_params[key]), atol=1e-2)

    def test_secure_dh_batched_dispatch_matches_plain(self):
        """DH secure aggregation composes with rounds_per_dispatch > 1
        (VERDICT r4 item 6): ONE X25519 pair-seed derivation per dispatch,
        each scanned round folding the round counter into every pair key —
        the aggregator-cannot-strip property holds for every round of the
        batch, and the committed model still matches the plain run."""
        from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh
        from bflc_demo_tpu.comm.identity import provision_wallets
        from bflc_demo_tpu.data import load_occupancy, iid_shards
        from bflc_demo_tpu.models import make_softmax_regression
        from bflc_demo_tpu.protocol import ProtocolConfig
        cfg = ProtocolConfig(client_num=8, comm_count=2,
                             aggregate_count=2, needed_update_count=3,
                             learning_rate=0.05, batch_size=16,
                             local_epochs=1)
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:800], ytr[:800], 8)
        wallets, _ = provision_wallets(8, b"mesh-secure-master-03")

        def run(secure, wallets=None):
            return run_federated_mesh(
                make_softmax_regression(), shards, (xte[:200], yte[:200]),
                cfg, rounds=4, rounds_per_dispatch=2, seed=3,
                secure_aggregation=secure, secure_wallets=wallets)

        plain = run(False)
        masked = run(True, wallets)
        assert masked.rounds_completed == 4
        for key in plain.final_params:
            np.testing.assert_allclose(
                np.asarray(masked.final_params[key]),
                np.asarray(plain.final_params[key]), atol=1e-2)

    def test_mask_keys_not_derived_from_public_seed(self):
        """VERDICT r4 weak #2b: shared-key masks must come from OS entropy,
        not the CLI-visible run seed.  _fresh_mask_key draws fresh entropy
        every call (two calls differ) and takes no seed input at all, so no
        function of the public config can reproduce the mask bits."""
        import inspect
        from bflc_demo_tpu.client.mesh_runtime import _fresh_mask_key
        k1, k2 = _fresh_mask_key(), _fresh_mask_key()
        assert not np.array_equal(np.asarray(jax.random.key_data(k1)),
                                  np.asarray(jax.random.key_data(k2)))
        assert inspect.signature(_fresh_mask_key).parameters == {}
        # and identical-seed secure runs still agree in the AGGREGATE
        # (masks cancel): covered by the *_matches_plain tests above


class TestSecureFedAvg:
    def test_matches_apply_selection_within_quantisation(self):
        rng = np.random.default_rng(5)
        mesh = client_axis_mesh(8)
        n = 16
        deltas = _vals(rng, n)
        params = {"W": jnp.asarray(rng.standard_normal((5, 2)), jnp.float32),
                  "b": jnp.asarray(rng.standard_normal((2,)), jnp.float32)}
        ns = jnp.asarray(rng.integers(100, 400, n), jnp.int32)
        sel = jnp.asarray(rng.random(n) < 0.5)
        got = secure_fedavg(mesh, deltas, ns, sel, params, 0.05,
                            jax.random.PRNGKey(6))
        want = apply_selection(params, deltas, ns, sel, 0.05)
        for k in params:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(want[k]),
                                       atol=0.05 * n / _SCALE + 1e-6)

    def test_unselected_clients_contribute_nothing(self):
        rng = np.random.default_rng(7)
        mesh = client_axis_mesh(4)
        n = 8
        deltas = _vals(rng, n)
        params = {"W": jnp.zeros((5, 2)), "b": jnp.zeros((2,))}
        ns = jnp.full((n,), 100, jnp.int32)
        sel = jnp.asarray([True] * 4 + [False] * 4)
        got = secure_fedavg(mesh, deltas, ns, sel, params, 1.0,
                            jax.random.PRNGKey(8))
        # replacing the unselected deltas entirely must not change the result
        deltas2 = {k: v.at[4:].set(999.0) for k, v in deltas.items()}
        got2 = secure_fedavg(mesh, deltas2, ns, sel, params, 1.0,
                             jax.random.PRNGKey(8))
        for k in params:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(got2[k]), atol=1e-4)

    def test_adversarial_huge_deltas_stay_bounded(self):
        """N clients with enormous deltas: pre-weighting clipping bounds the
        weighted sum by clip, so the fixed-point psum cannot wrap (advisor
        finding: post-weighting clipping let each client contribute +/-clip
        and the true sum reach N*clip)."""
        rng = np.random.default_rng(11)
        mesh = client_axis_mesh(8)
        n, clip = 16, 8.0
        deltas = {k: v * 1e6 for k, v in _vals(rng, n).items()}  # all clipped
        params = {"W": jnp.zeros((5, 2)), "b": jnp.zeros((2,))}
        ns = jnp.full((n,), 100, jnp.int32)
        sel = jnp.ones((n,), bool)
        got = secure_fedavg(mesh, deltas, ns, sel, params, 1.0,
                            jax.random.PRNGKey(12), clip=clip)
        # reference: weighted mean of the CLIPPED deltas — every entry of a
        # huge-magnitude delta clips to +/-clip, so |result| == clip exactly
        want = apply_selection(
            params,
            {k: jnp.clip(v, -clip, clip) for k, v in deltas.items()},
            ns, sel, 1.0)
        for k in params:
            got_k = np.asarray(got[k])
            np.testing.assert_allclose(got_k, np.asarray(want[k]),
                                       atol=n / _SCALE + 1e-6)
            assert np.all(np.abs(got_k) <= clip + 1e-3)   # no int32 wrap

    def test_nan_delta_cannot_corrupt_aggregate(self):
        """clip propagates NaN and the int32 cast of NaN is implementation-
        defined, so NaN deltas must be neutralised before quantisation."""
        rng = np.random.default_rng(13)
        mesh = client_axis_mesh(4)
        n = 8
        deltas = _vals(rng, n)
        poisoned = {k: v.at[2].set(jnp.nan) for k, v in deltas.items()}
        params = {"W": jnp.zeros((5, 2)), "b": jnp.zeros((2,))}
        ns = jnp.full((n,), 100, jnp.int32)
        sel = jnp.ones((n,), bool)
        got = secure_fedavg(mesh, poisoned, ns, sel, params, 1.0,
                            jax.random.PRNGKey(14))
        # NaN client behaves as a zero delta; everyone else aggregates intact
        zeroed = {k: v.at[2].set(0.0) for k, v in deltas.items()}
        want = apply_selection(params, zeroed, ns, sel, 1.0)
        for k in params:
            assert np.all(np.isfinite(np.asarray(got[k])))
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(want[k]),
                                       atol=n / _SCALE + 1e-6)
