"""Host-runtime edge cases: stale-epoch retry, store hygiene, compute-plane
guards — the seams between FLNode, UpdateStore, ComputePlane and the ledger."""

import jax.numpy as jnp
import numpy as np
import pytest

from bflc_demo_tpu.client.runtime import FLNode, ComputePlane
from bflc_demo_tpu.comm import UpdateStore
from bflc_demo_tpu.data import load_occupancy, iid_shards, one_hot
from bflc_demo_tpu.ledger import make_ledger, LedgerStatus
from bflc_demo_tpu.models import make_softmax_regression
from bflc_demo_tpu.protocol import ProtocolConfig

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.001,
                     batch_size=50)
MODEL = make_softmax_regression()


def _setup():
    xtr, ytr, _, _ = load_occupancy()
    shards = iid_shards(xtr[:1200], ytr[:1200], CFG.client_num)
    nodes = [FLNode(address=f"0x{i:03x}", x=jnp.asarray(sx),
                    y=jnp.asarray(one_hot(sy, 2)), model=MODEL, cfg=CFG)
             for i, (sx, sy) in enumerate(shards)]
    ledger = make_ledger(CFG, backend="python")
    for n in nodes:
        n.register(ledger)
    return nodes, ledger, UpdateStore(), MODEL.init_params(0)


def test_stale_epoch_upload_leaves_node_retryable():
    """If the round advances between a node reading the epoch and uploading,
    FLNode._train drops the rejected payload from the store and leaves
    trained_epoch untouched so the node retries at the new epoch (reviewed
    leak/wedge case) — driven through the node's real upload path with a
    stale epoch value."""
    nodes, ledger, store, params = _setup()
    trainer = nodes[2]
    # the race, through the real path: the node acts on a stale epoch read
    out = trainer._train(ledger, store, params, epoch=7)
    assert out is None
    assert len(store) == 0                  # rejected payload reclaimed
    assert trainer.trained_epoch == CFG.initial_trained_epoch
    # next event sees the true epoch and succeeds
    acted = trainer.step(ledger, store, params)
    assert acted == "train:OK"
    assert trainer.trained_epoch == 0
    assert len(store) == 1


def test_cap_rejection_drops_payload_from_store():
    nodes, ledger, store, params = _setup()
    for n in nodes[2:5]:                    # fills the 3-update round
        assert n.step(ledger, store, params) == "train:OK"
    assert len(store) == 3
    late = nodes[5]
    assert late.step(ledger, store, params) == "train:CAP_REACHED"
    assert len(store) == 3                  # late payload not retained
    assert late.trained_epoch == 0          # done for this epoch anyway


def test_compute_plane_clears_round_payloads():
    nodes, ledger, store, params = _setup()
    for n in nodes[2:5]:
        n.step(ledger, store, params)
    for n in nodes[:2]:                     # committee scores
        n.step(ledger, store, params)
    assert ledger.aggregate_ready()
    plane = ComputePlane(CFG)
    new_params = plane.maybe_aggregate(ledger, store, params)
    assert new_params is not None
    assert len(store) == 0                  # round payloads reclaimed
    assert ledger.epoch == 1


def test_compute_plane_noop_when_not_ready():
    nodes, ledger, store, params = _setup()
    plane = ComputePlane(CFG)
    assert plane.maybe_aggregate(ledger, store, params) is None


def test_committee_node_waits_for_full_round():
    nodes, ledger, store, params = _setup()
    comm = nodes[0]
    assert comm.step(ledger, store, params) is None     # nothing to score
    nodes[2].step(ledger, store, params)
    assert comm.step(ledger, store, params) is None     # still under-filled
    nodes[3].step(ledger, store, params)
    nodes[4].step(ledger, store, params)
    assert comm.step(ledger, store, params) == "score:OK"
    # one score per epoch (main.py:221-222 semantics)
    assert comm.step(ledger, store, params) is None
