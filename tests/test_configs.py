"""The five benchmark configs run end-to-end through the full protocol.

CI uses scaled-down geometry (tiny protocol + small data) so the suite stays
fast on the virtual CPU mesh; the full benchmark geometries run on TPU via
eval.configs defaults (exercised by bench/driver runs) and the `slow` marks.
"""

import os

import numpy as np
import pytest

from bflc_demo_tpu.eval.configs import (
    CONFIGS, config2_lenet_cifar10, config3_femnist_sampled,
    config5_transformer_sst2)
from bflc_demo_tpu.protocol import ProtocolConfig

TINY = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                      needed_update_count=3, learning_rate=0.05,
                      batch_size=16, local_epochs=1)


def _check(res, rounds, clients, uploads, scores):
    assert res.rounds_completed == rounds
    assert all(np.isfinite(a) for _, a in res.accuracy_history)
    assert res.ledger_log_size == clients + rounds * (uploads + scores + 1)


def test_config2_lenet_noniid_tiny():
    res = config2_lenet_cifar10(rounds=2, n_data=1500, cfg=TINY)
    _check(res, 2, 8, 3, 2)


@pytest.mark.slow
def test_config3_sampled_participation_tiny():
    """Sampled-clients regime: only uploader+committee slots are active.

    slow tier (PR 9 budget reclaim): 63 s measured on the 2-core CI box
    — mostly XLA compile of the 30-client sampled-participation round
    program; active participation stays tier-1-covered by
    tests/test_secure.py's active-participation secure run, and the
    full config3 geometry runs in bench/driver sweeps."""
    cfg = ProtocolConfig(client_num=30, comm_count=2, aggregate_count=2,
                         needed_update_count=3, learning_rate=0.05,
                         batch_size=10, local_epochs=1)
    res = config3_femnist_sampled(rounds=2, n_data=3000, cfg=cfg)
    _check(res, 2, 30, 3, 2)


@pytest.mark.slow
def test_config4_resnet_tiny():
    """ResNet path with active participation + chunked remat training.

    slow tier: ~2 min of CPU XLA compile for the remat ResNet program —
    the two config4 tiny runs alone would eat a third of the tier-1 time
    budget on a 2-core box (measured 132 s + 192 s of a 870 s budget)."""
    from bflc_demo_tpu.client import run_federated_mesh
    from bflc_demo_tpu.models import make_resnet18
    from bflc_demo_tpu.data.synthetic import synthetic_image_classification
    from bflc_demo_tpu.data import iid_shards
    x, y = synthetic_image_classification(600, (16, 16, 3), 4, seed=0)
    shards = iid_shards(x[:480], y[:480], TINY.client_num)
    res = run_federated_mesh(
        make_resnet18((16, 16, 3), 4), shards, (x[480:], y[480:]), TINY,
        rounds=1, participation="active", client_chunk=2, remat=True)
    _check(res, 1, 8, 3, 2)


@pytest.mark.slow
def test_config4_secure_tiny():
    """configs[3]'s secure-aggregation variant end-to-end: ResNet path with
    X25519-masked merge through active participation + chunked remat (the
    exact plumbing config4(secure=True) selects).  slow tier: see
    test_config4_resnet_tiny — the masked-merge compile is the priciest
    program in the suite (192 s measured on the 2-core CI box)."""
    from bflc_demo_tpu.client import run_federated_mesh
    from bflc_demo_tpu.comm.identity import provision_wallets
    from bflc_demo_tpu.models import make_resnet18
    from bflc_demo_tpu.data.synthetic import synthetic_image_classification
    from bflc_demo_tpu.data import iid_shards
    x, y = synthetic_image_classification(600, (16, 16, 3), 4, seed=0)
    shards = iid_shards(x[:480], y[:480], TINY.client_num)
    wallets, _ = provision_wallets(TINY.client_num, b"config4-test-seed-01")
    res = run_federated_mesh(
        make_resnet18((16, 16, 3), 4), shards, (x[480:], y[480:]), TINY,
        rounds=1, participation="active", client_chunk=2, remat=True,
        secure_aggregation=True, secure_wallets=wallets)
    _check(res, 1, 8, 3, 2)


@pytest.mark.slow
def test_config5_transformer_text_tiny():
    """slow tier (PR 9 budget reclaim): 47 s on the 2-core CI box —
    transformer round-program compile for the STRETCH config; the
    transformer model itself stays tier-1-covered by
    tests/test_models.py and the long-context suites."""
    res = config5_transformer_sst2(rounds=2, n_data=700, cfg=TINY)
    _check(res, 2, 8, 3, 2)


def test_registry_names():
    # config0..config5: BASELINE.json's published list (configs[0..4] ->
    # config0, config2..config5) plus the occupancy parity anchor (config1)
    assert list(CONFIGS) == [f"config{i}" for i in range(6)]


def test_estimate_flops_and_mfu():
    """estimate_flops=True reads XLA's compiled cost analysis for ONE round
    (the MFU numerator) and reuses the AOT executable for every round."""
    from bflc_demo_tpu.client import run_federated_mesh
    from bflc_demo_tpu.data import load_occupancy, iid_shards
    from bflc_demo_tpu.models import make_softmax_regression
    xtr, ytr, xte, yte = load_occupancy()
    res = run_federated_mesh(
        make_softmax_regression(), iid_shards(xtr[:800], ytr[:800], 8),
        (xte[:200], yte[:200]), TINY, rounds=2, estimate_flops=True)
    assert res.rounds_completed == 2
    assert res.flops_per_round > 0          # CPU backend reports flops
    # mfu(): flops / mean round time / peak
    mfu = res.mfu(peak_flops=1e12)
    times = res.round_times_s[1:]
    want = res.flops_per_round / (sum(times) / len(times)) / 1e12
    assert abs(mfu - want) < 1e-12
    assert res.mfu(peak_flops=0) == 0.0


def test_chip_peak_lookup():
    from bflc_demo_tpu.eval.mfu import chip_peak_flops
    import jax
    # CPU platform -> None; env override wins
    assert chip_peak_flops(jax.devices()[0]) is None
    import os
    os.environ["BFLC_TPU_PEAK_TFLOPS"] = "197"
    try:
        assert chip_peak_flops(jax.devices()[0]) == 197e12
    finally:
        del os.environ["BFLC_TPU_PEAK_TFLOPS"]


def test_config0_mlp_mnist_tiny():
    """BASELINE configs[0]: 2-layer MLP, MNIST shapes, 4-client IID."""
    from bflc_demo_tpu.eval.configs import config0_mlp_mnist
    res = config0_mlp_mnist(rounds=2, n_data=1200)
    _check(res, 2, 4, 2, 2)
    assert all(np.isfinite(a) for _, a in res.accuracy_history)


# Convergence-bar tests.  Tiering is a 1-core-CI budget decision, measured:
# this box has ONE CPU core and XLA CPU convs are single-threaded, so a
# conv-model protocol round costs 25-260 s regardless of how far the
# geometry shrinks (cost ≈ padded-shard steps × active slots, and the
# Dirichlet max-shard stays ~10x the batch at any n_data).  `slow` tests
# fit the regular suite (~7 min total); `heavy` tests (full conv configs)
# run with BFLC_HEAVY_TESTS=1 — their trajectories below are MEASURED in
# this environment, not aspirational.

heavy = pytest.mark.skipif(
    os.environ.get("BFLC_HEAVY_TESTS", "0") in ("", "0"),
    reason="conv-config convergence needs ~35 min/test on this 1-core box; "
           "set BFLC_HEAVY_TESTS=1 (measured trajectories in docstrings)")


@pytest.mark.slow
def test_config2_converges():
    """Non-IID LeNet/CIFAR beats chance clearly at a small geometry.

    Measured (this box, seed 0): 0.413 by round 7 at 28 s/round — chance
    is 0.1, bar 0.35.  Full geometry (20 clients, n_data=2400, rounds=12)
    measured 0.84 by round 11; run it via BFLC_HEAVY_TESTS tier below."""
    res = config2_lenet_cifar10(
        rounds=8, n_data=1200,
        cfg=ProtocolConfig(client_num=8, comm_count=2, aggregate_count=3,
                           needed_update_count=4, learning_rate=0.05,
                           batch_size=32, local_epochs=4))
    assert res.best_accuracy() > 0.35       # 10 classes, chance = 0.1


@pytest.mark.slow
def test_config5_converges():
    """Transformer text classifier learns the synthetic SST-2 task.

    Measured (this box, seed 0): 0.996 by round 4 in ~130 s total
    (binary, chance 0.5)."""
    res = config5_transformer_sst2(
        rounds=5, n_data=1200,
        cfg=ProtocolConfig(client_num=8, comm_count=2, aggregate_count=3,
                           needed_update_count=4, learning_rate=0.05,
                           batch_size=16, local_epochs=2))
    assert res.best_accuracy() > 0.8


@heavy
@pytest.mark.slow
def test_config2_converges_full_geometry():
    """Full config-2 geometry. Measured: 0.11 plateau through round 5,
    then 0.37/0.45/0.74/0.84 by round 11 (seed 0, ~8 min/4 rounds)."""
    res = config2_lenet_cifar10(rounds=12, n_data=2400)
    assert res.best_accuracy() > 0.5


@heavy
@pytest.mark.slow
def test_config3_converges():
    """FEMNIST sampled participation clears the 62-class bar (chance
    ~0.016).  Measured trajectories (seed 0): 0.587 by round 7 at the
    30-client geometry below (~35 min on this box); 0.97 by round 11 at
    the full 100-client geometry, n_data=8000."""
    res = config3_femnist_sampled(
        rounds=8, n_data=3000,
        cfg=ProtocolConfig(client_num=30, comm_count=3, aggregate_count=3,
                           needed_update_count=5, learning_rate=0.05,
                           batch_size=20, local_epochs=4))
    assert res.best_accuracy() > 0.4


@heavy
@pytest.mark.slow
def test_config4_secure_preset_full_shapes():
    """The actual config4(secure=True) preset at full CIFAR-100 shapes —
    heavy tier only (ResNet-18 conv rounds are ~40 min single-threaded on
    this 1-core box; the accelerator sweep covers this nightly)."""
    from bflc_demo_tpu.eval.configs import config4_resnet_cifar100
    res = config4_resnet_cifar100(rounds=1, n_data=600, cfg=TINY,
                                  secure=True)
    _check(res, 1, 8, 3, 2)


# Config 4 (ResNet-18) has NO CPU convergence tier at all, measured not
# assumed: even at 16x16x3 / 4 classes / 8 clients / 6 rounds the run
# exceeded a 30-minute timeout on this box (fixed 64-512-channel convs are
# ~40 min/round single-threaded), so any bar asserted here would be a test
# that never ran.  Protocol correctness runs in test_config4_resnet_tiny
# above; convergence numbers come from the accelerator via
# tools/tpu_bench_configs.py (best_acc recorded per config in
# TPU_RESULTS.md whenever the TPU tunnel is reachable).


def test_run_with_runtime_guards():
    from bflc_demo_tpu.eval.configs import run_with_runtime
    from bflc_demo_tpu.models import make_softmax_regression
    import numpy as np
    shards = [(np.zeros((20, 5), np.float32), np.zeros(20, np.int64))] * 8
    test = (np.zeros((10, 5), np.float32), np.zeros(10, np.int64))
    with pytest.raises(ValueError):
        run_with_runtime(make_softmax_regression(), shards, test, TINY,
                         runtime="nope")
    with pytest.raises(ValueError):   # processes needs a registered factory
        run_with_runtime(make_softmax_regression(), shards, test, TINY,
                         runtime="processes")
    with pytest.raises(ValueError):   # mesh-only options on host runtime
        run_with_runtime(make_softmax_regression(), shards, test, TINY,
                         runtime="host", participation="active")
