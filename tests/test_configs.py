"""The five benchmark configs run end-to-end through the full protocol.

CI uses scaled-down geometry (tiny protocol + small data) so the suite stays
fast on the virtual CPU mesh; the full benchmark geometries run on TPU via
eval.configs defaults (exercised by bench/driver runs) and the `slow` marks.
"""

import numpy as np
import pytest

from bflc_demo_tpu.eval.configs import (
    CONFIGS, config2_lenet_cifar10, config3_femnist_sampled,
    config5_transformer_sst2)
from bflc_demo_tpu.protocol import ProtocolConfig

TINY = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=2,
                      needed_update_count=3, learning_rate=0.05,
                      batch_size=16, local_epochs=1)


def _check(res, rounds, clients, uploads, scores):
    assert res.rounds_completed == rounds
    assert all(np.isfinite(a) for _, a in res.accuracy_history)
    assert res.ledger_log_size == clients + rounds * (uploads + scores + 1)


def test_config2_lenet_noniid_tiny():
    res = config2_lenet_cifar10(rounds=2, n_data=1500, cfg=TINY)
    _check(res, 2, 8, 3, 2)


def test_config3_sampled_participation_tiny():
    """Sampled-clients regime: only uploader+committee slots are active."""
    cfg = ProtocolConfig(client_num=30, comm_count=2, aggregate_count=2,
                         needed_update_count=3, learning_rate=0.05,
                         batch_size=10, local_epochs=1)
    res = config3_femnist_sampled(rounds=2, n_data=3000, cfg=cfg)
    _check(res, 2, 30, 3, 2)


def test_config4_resnet_tiny():
    """ResNet path with active participation + chunked remat training."""
    from bflc_demo_tpu.client import run_federated_mesh
    from bflc_demo_tpu.models import make_resnet18
    from bflc_demo_tpu.data.synthetic import synthetic_image_classification
    from bflc_demo_tpu.data import iid_shards
    x, y = synthetic_image_classification(600, (16, 16, 3), 4, seed=0)
    shards = iid_shards(x[:480], y[:480], TINY.client_num)
    res = run_federated_mesh(
        make_resnet18((16, 16, 3), 4), shards, (x[480:], y[480:]), TINY,
        rounds=1, participation="active", client_chunk=2, remat=True)
    _check(res, 1, 8, 3, 2)


def test_config5_transformer_text_tiny():
    res = config5_transformer_sst2(rounds=2, n_data=700, cfg=TINY)
    _check(res, 2, 8, 3, 2)


def test_registry_names():
    assert list(CONFIGS) == [f"config{i}" for i in range(1, 6)]


@pytest.mark.slow
def test_config2_converges():
    """Synthetic CIFAR is learnable: non-IID LeNet run beats chance clearly.

    Measured trajectory at this geometry (padded shards, local_epochs=4):
    plateau ~0.13 through round 5, then 0.37 -> 0.45 -> 0.74 -> 0.84 by
    round 11 — the 0.5 bar has a wide margin but still requires the conv
    model to actually train (chance = 0.1)."""
    res = config2_lenet_cifar10(rounds=12, n_data=2400)
    assert res.best_accuracy() > 0.5        # 10 classes, chance = 0.1


@pytest.mark.slow
def test_config3_converges():
    """FEMNIST sampled-participation run clears the 62-class bar (chance
    ~0.016; measured 0.97 by round 11 at the full geometry, n_data=8000)."""
    res = config3_femnist_sampled(rounds=12, n_data=8000)
    assert res.best_accuracy() > 0.5


@pytest.mark.slow
def test_config5_converges():
    """Transformer text classifier learns the synthetic SST-2 task
    (binary, chance 0.5; measured 0.995 by round 7 at n_data=2000)."""
    res = config5_transformer_sst2(rounds=8, n_data=2000)
    assert res.best_accuracy() > 0.8
