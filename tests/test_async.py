"""Asynchronous buffered aggregation (FedBuff on the certified op
stream; ISSUE 9): the async op family's ledger semantics, the
synchronous-path byte-identity pin, the heavytail chaos profile, the
writer's admission/trigger path under a BFT quorum, and an end-to-end
async chaos drill whose invariants (single certified history, monotone
progress, acked-upload durability) must hold with the round barrier
down.
"""

import dataclasses
import hashlib
import struct

import numpy as np
import pytest

from bflc_demo_tpu.ledger import (LedgerStatus, async_enabled,
                                  make_ledger, staleness_weight)
from bflc_demo_tpu.ledger.base import (ascores_sign_payload,
                                       encode_aupload_op,
                                       encode_ascores_op)
from bflc_demo_tpu.ledger.pyledger import PyLedger
from bflc_demo_tpu.protocol.constants import ProtocolConfig

ACFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                      needed_update_count=3, learning_rate=0.05,
                      batch_size=16, async_buffer=3,
                      max_staleness=2).validate()


def _sync_scripted_ledger() -> PyLedger:
    """The scripted sync round the byte-identity pin hashes."""
    led = PyLedger(6, 2, 2, 3, -999)
    addrs = [f"addr-{i:02d}" for i in range(6)]
    for a in addrs:
        assert led.register_node(a) == LedgerStatus.OK
    committee = led.committee()
    trainers = [a for a in addrs if a not in committee]
    for j, a in enumerate(trainers[:3]):
        h = hashlib.sha256(a.encode()).digest()
        assert led.upload_local_update(a, h, 10 + j, 0.5 + j,
                                       0) == LedgerStatus.OK
    for a in committee:
        assert led.upload_scores(a, 0,
                                 [0.1, 0.9, 0.4]) == LedgerStatus.OK
    assert led.commit_model(b"\x42" * 32, 0) == LedgerStatus.OK
    return led


def _async_ledger(cfg=ACFG):
    led = make_ledger(cfg)
    for i in range(cfg.client_num):
        assert led.register_node(f"c{i}") == LedgerStatus.OK
    committee = led.committee()
    trainers = [f"c{i}" for i in range(cfg.client_num)
                if f"c{i}" not in committee]
    return led, committee, trainers


class TestSyncPathPinned:
    """--async-buffer 0 (the default) keeps the synchronous protocol
    byte-for-byte: chain bytes, state bytes, and op admissibility."""

    # digests captured from the pre-async tree (PR 9): any drift in the
    # sync op codec or the canonical state layout fails here
    GOLDEN_HEAD = ("14656aaf3dd7a54729706d2e84bd0cd3"
                   "257235d2f628cfeafdad3a970fb14bc9")
    GOLDEN_STATE = ("dfdd082f6fe7ccb00e8182858815cb54"
                    "6e72d64b468ff24d076a03d6e53c8b9d")

    def test_sync_chain_and_state_bytes_unchanged(self):
        led = _sync_scripted_ledger()
        assert led.log_head().hex() == self.GOLDEN_HEAD
        assert hashlib.sha256(
            led.encode_state()).hexdigest() == self.GOLDEN_STATE

    def test_sync_ledger_refuses_the_async_op_family(self):
        led = _sync_scripted_ledger()
        assert led.async_upload("addr-00", b"\0" * 32, 5, 0.1,
                                0) == LedgerStatus.BAD_ARG
        assert led.apply_op(encode_aupload_op(
            "addr-00", b"\0" * 32, 5, 0.1, 0)) == LedgerStatus.BAD_ARG
        assert led.apply_op(encode_ascores_op(
            "addr-00", [(0, 0.5)])) == LedgerStatus.BAD_ARG
        from bflc_demo_tpu.ledger.snapshot import decode_state
        assert decode_state(led.encode_state())["async"] is None

    def test_async_legacy_env_pins_sync(self, monkeypatch):
        monkeypatch.setenv("BFLC_ASYNC_LEGACY", "1")
        assert not async_enabled(ACFG)
        led = make_ledger(ACFG)
        # either backend may serve the pinned-sync chain; neither runs
        # the async op family
        assert getattr(led, "async_buffer", 0) == 0

    def test_native_backend_refused_for_async(self):
        with pytest.raises(ValueError, match="python ledger backend"):
            make_ledger(ACFG, backend="native")

    def test_async_buffer_must_fit_trainer_population(self):
        with pytest.raises(ValueError, match="trainer population"):
            dataclasses.replace(ACFG, async_buffer=5).validate()


class TestAsyncLedger:
    def test_admission_staleness_dup_cap_and_commit(self):
        led, committee, trainers = _async_ledger()
        for j, s in enumerate(trainers[:3]):
            assert led.async_upload(
                s, hashlib.sha256(s.encode()).digest(), 10 + j,
                1.0 + j, 0) == LedgerStatus.OK
        assert led.async_buffer_depth == 3
        # one in-flight delta per sender; buffer bound
        assert led.async_upload(trainers[0], b"\1" * 32, 5, 0.1,
                                0) == LedgerStatus.DUPLICATE
        assert led.async_upload(trainers[3], b"\2" * 32, 5, 0.1,
                                0) == LedgerStatus.CAP_REACHED
        # scoring: committee only, no epoch gate, unknown aseqs skipped
        assert led.async_scores(trainers[0],
                                [(0, 0.5)]) == LedgerStatus.NOT_COMMITTEE
        assert led.async_scores(committee[0],
                                [(99, 0.5)]) == LedgerStatus.NOT_READY
        assert led.async_scores(
            committee[0], [(0, 0.2), (1, 0.9), (2, 0.5)]) == \
            LedgerStatus.OK
        entries, selected, weights, loss = led.async_selection(3)
        # ranked by median score desc: aseq 1 (0.9) then 2 (0.5)
        assert selected == [1, 2]
        assert weights == [10.0, 11.0, 12.0]    # staleness 0: raw n
        assert led.async_commit(b"\x13" * 32, 0,
                                3) == LedgerStatus.OK
        assert led.epoch == 1 and led.async_buffer_depth == 0
        assert led.last_global_loss == pytest.approx(
            (11 * 2.0 + 12 * 3.0) / 23, rel=1e-5)

    def test_staleness_stamp_discount_and_cap(self):
        led, committee, trainers = _async_ledger()
        for epoch in range(3):          # advance 3 async epochs
            assert led.async_upload(
                trainers[0], bytes([epoch]) * 32, 10, 1.0,
                epoch) == LedgerStatus.OK
            assert led.async_commit(bytes([epoch]) * 32, epoch,
                                    1) == LedgerStatus.OK
        assert led.epoch == 3
        # a delta trained on epoch 1 arrives now: staleness 2, admitted
        assert led.async_upload(trainers[1], b"\7" * 32, 8, 1.0,
                                1) == LedgerStatus.OK
        e = led.async_buffer_view()[-1]
        assert e.staleness == 2 and e.base_epoch == 1
        _, _, weights, _ = led.async_selection(1)
        assert weights[0] == pytest.approx(8 * staleness_weight(2))
        # epoch 0 is now 3 behind: over max_staleness=2 -> refused
        assert led.async_upload(trainers[2], b"\x08" * 32, 8, 1.0,
                                0) == LedgerStatus.WRONG_EPOCH
        # the future is never a valid base
        assert led.async_upload(trainers[2], b"\x08" * 32, 8, 1.0,
                                7) == LedgerStatus.BAD_ARG

    def test_replica_replay_reproduces_head_and_state(self):
        led, committee, trainers = _async_ledger()
        for j, s in enumerate(trainers[:3]):
            led.async_upload(s, hashlib.sha256(s.encode()).digest(),
                             10 + j, 1.0, 0)
        led.async_scores(committee[0], [(0, 0.3), (2, 0.8)])
        led.async_commit(b"\x21" * 32, 0, 2)
        replica = make_ledger(ACFG)
        for i in range(led.log_size()):
            assert replica.apply_op(led.log_op(i)) == LedgerStatus.OK
        assert replica.log_head() == led.log_head()
        assert replica.state_digest() == led.state_digest()
        assert replica.async_buffer_depth == 1

    def test_validate_op_leaves_async_state_untouched(self):
        led, committee, trainers = _async_ledger()
        led.async_upload(trainers[0], b"\3" * 32, 10, 1.0, 0)
        op = encode_aupload_op(trainers[1], b"\4" * 32, 5, 0.5, 0)
        before = led.state_digest()
        assert led.validate_op(op) == LedgerStatus.OK
        assert led.state_digest() == before
        assert led.async_buffer_depth == 1

    def test_state_roundtrip_with_buffered_entries(self):
        from bflc_demo_tpu.ledger.snapshot import restore_snapshot
        led, committee, trainers = _async_ledger()
        led.async_upload(trainers[0], b"\5" * 32, 10, 1.5, 0)
        led.async_scores(committee[1], [(0, 0.7)])
        blob = led.encode_state()
        r = restore_snapshot(blob, ACFG, led.log_size(),
                             led.log_head())
        assert r.state_digest() == led.state_digest()
        assert r.async_buffer_depth == 1
        # the restored replica keeps applying async ops
        assert r.async_upload(trainers[1], b"\6" * 32, 5, 0.5,
                              0) == LedgerStatus.OK

    def test_acommit_epoch_and_k_guards(self):
        led, committee, trainers = _async_ledger()
        assert led.async_commit(b"\0" * 32, 0,
                                1) == LedgerStatus.NOT_READY
        led.async_upload(trainers[0], b"\1" * 32, 5, 0.5, 0)
        assert led.async_commit(b"\0" * 32, 5,
                                1) == LedgerStatus.WRONG_EPOCH
        assert led.async_commit(b"\0" * 32, 0,
                                2) == LedgerStatus.NOT_READY


class TestHeavytailProfile:
    def test_seeded_deterministic_per_client_delays(self):
        from bflc_demo_tpu.chaos.schedule import FaultSchedule, PROFILES
        assert "heavytail" in PROFILES
        mk = lambda: FaultSchedule(        # noqa: E731
            42, duration_s=60, n_clients=6, n_standbys=1,
            n_validators=4, profile="heavytail")
        s1, s2 = mk(), mk()
        assert not s1.events                # pure straggler regime
        assert set(s1.wire_windows) == {f"client-{i}"
                                        for i in range(6)}
        d1 = [w.delay_ms for ws in s1.wire_windows.values()
              for w in ws]
        d2 = [w.delay_ms for ws in s2.wire_windows.values()
              for w in ws]
        assert d1 == d2
        # heavy tail: the max delay dominates the median
        assert max(d1) > 3 * sorted(d1)[len(d1) // 2]
        for ws in s1.wire_windows.values():
            assert all(w.mode == "delay" and w.p == 1.0 for w in ws)
        spec = s1.wire_spec("client-0", 0.0, {"writer": 5000})
        assert spec and spec["windows"][0]["mode"] == "delay"


class TestAsyncService:
    """Writer admission/trigger/certification over real sockets with a
    BFT validator quorum re-executing the async op family."""

    @pytest.fixture
    def fleet(self):
        from bflc_demo_tpu.comm.bft import (ValidatorNode,
                                            provision_validators)
        from bflc_demo_tpu.comm.identity import provision_wallets
        from bflc_demo_tpu.comm.ledger_service import (
            CoordinatorClient, LedgerServer)
        from bflc_demo_tpu.utils.serialization import pack_pytree
        cfg = dataclasses.replace(ACFG, client_num=8,
                                  needed_update_count=4,
                                  max_staleness=4).validate()
        wallets, _ = provision_wallets(8, b"async-test-seed")
        vws, vkeys = provision_validators(4, b"async-test-validators")
        nodes = [ValidatorNode(cfg, w, i, validator_keys=vkeys)
                 for i, w in enumerate(vws)]
        for v in nodes:
            v.start()
        blob0 = pack_pytree({"W": np.zeros((5, 2), np.float32),
                             "b": np.zeros((2,), np.float32)})
        srv = LedgerServer(cfg, blob0,
                           bft_validators=[(v.host, v.port)
                                           for v in nodes],
                           bft_keys=vkeys)
        srv.start()
        cl = CoordinatorClient(srv.host, srv.port)
        try:
            yield cfg, wallets, srv, cl, nodes
        finally:
            cl.close()
            srv.close()
            for v in nodes:
                v.close()

    @staticmethod
    def _sign(w, kind, epoch, payload):
        from bflc_demo_tpu.comm.identity import _op_bytes
        return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()

    def _aupload(self, cl, w, i, base):
        from bflc_demo_tpu.utils.serialization import pack_pytree
        blob = pack_pytree({"W": np.full((5, 2), 0.1 * (i + 1),
                                         np.float32),
                            "b": np.zeros((2,), np.float32)})
        d = hashlib.sha256(blob).digest()
        payload = d + struct.pack("<qd", 10 + i, 1.0)
        return cl.request(
            "aupload", addr=w.address, blob=blob, hash=d.hex(),
            n=10 + i, cost=1.0, base_epoch=base,
            tag=self._sign(w, "aupload", base, payload))

    def test_buffered_round_certifies_and_triggers_at_k(self, fleet):
        from bflc_demo_tpu.comm.identity import _op_bytes
        cfg, wallets, srv, cl, nodes = fleet
        for w in wallets:
            assert cl.request(
                "register", addr=w.address,
                pubkey=w.public_bytes.hex(),
                tag=self._sign(w, "register", 0, b""))["ok"]
        committee = set(cl.request("committee")["committee"])
        trainers = [w for w in wallets if w.address not in committee]
        comm_ws = [w for w in wallets if w.address in committee]

        r = self._aupload(cl, trainers[0], 0, 0)
        assert r["ok"] and r.get("cert"), r
        assert self._aupload(cl, trainers[1], 1, 0)["ok"]
        # replayed tag -> DUPLICATE, never a second buffer entry
        r = self._aupload(cl, trainers[0], 0, 0)
        assert r["status"] == "DUPLICATE", r

        au = cl.request("aupdates")
        assert au["ok"] and len(au["updates"]) == 2
        pairs = [(u["aseq"], 0.5 + 0.1 * i)
                 for i, u in enumerate(au["updates"])]
        w = comm_ws[0]
        r = cl.request(
            "ascores", addr=w.address,
            pairs=[[a, s] for a, s in pairs],
            tag=w.sign(_op_bytes("ascores", w.address, 0,
                                 ascores_sign_payload(pairs))).hex())
        assert r["ok"], r

        # the K-th admission aggregates inside its own ack
        r = self._aupload(cl, trainers[2], 2, 0)
        assert r["ok"] and r["epoch"] == 1, r
        info = cl.request("info")
        assert info["epoch"] == 1
        assert info["certified_size"] == info["log_size"]
        assert info["async_buffer_depth"] == 0

        # a late delta trained on epoch 0 lands staleness-tagged
        assert self._aupload(cl, trainers[3], 3, 0)["ok"]
        au = cl.request("aupdates")
        assert au["updates"][0]["staleness"] == 1

        # validators re-executed the whole family: heads agree
        from bflc_demo_tpu.comm.bft import ValidatorClient
        for v in nodes:
            vc = ValidatorClient((v.host, v.port))
            try:
                vinfo = vc.request("info", at=info["log_size"])
            finally:
                vc.close()
            if vinfo.get("log_size") == info["log_size"]:
                assert vinfo["head_at"] == info["log_head"]

    def test_sync_ops_refused_in_async_mode(self, fleet):
        """One protocol per chain: a client whose BFLC_ASYNC_LEGACY
        disagrees with the fleet's must not interleave sync rounds
        into an async chain."""
        from bflc_demo_tpu.utils.serialization import pack_pytree
        cfg, wallets, srv, cl, nodes = fleet
        w = wallets[0]
        cl.request("register", addr=w.address,
                   pubkey=w.public_bytes.hex(),
                   tag=self._sign(w, "register", 0, b""))
        blob = pack_pytree({"W": np.zeros((5, 2), np.float32),
                            "b": np.zeros((2,), np.float32)})
        d = hashlib.sha256(blob).digest()
        payload = d + struct.pack("<qd", 10, 1.0)
        r = cl.request("upload", addr=w.address, blob=blob,
                       hash=d.hex(), n=10, cost=1.0, epoch=0,
                       tag=self._sign(w, "upload", 0, payload))
        assert not r["ok"] and "async mode" in r.get("error", ""), r
        r = cl.request("scores", addr=w.address, epoch=0, scores=[0.5],
                       tag="00")
        assert not r["ok"] and "async mode" in r.get("error", ""), r

    def test_forged_ascores_tag_refused(self, fleet):
        from bflc_demo_tpu.comm.identity import _op_bytes
        cfg, wallets, srv, cl, nodes = fleet
        for w in wallets:
            cl.request("register", addr=w.address,
                       pubkey=w.public_bytes.hex(),
                       tag=self._sign(w, "register", 0, b""))
        committee = set(cl.request("committee")["committee"])
        trainers = [w for w in wallets if w.address not in committee]
        comm_w = [w for w in wallets if w.address in committee][0]
        assert self._aupload(cl, trainers[0], 0, 0)["ok"]
        # a trainer signing AS a committee member must fail auth
        forged = trainers[1].sign(_op_bytes(
            "ascores", comm_w.address, 0,
            ascores_sign_payload([(0, 0.9)]))).hex()
        r = cl.request("ascores", addr=comm_w.address,
                       pairs=[[0, 0.9]], tag=forged)
        assert not r["ok"] and r["status"] == "BAD_ARG"


class TestAsyncQuantizedDeltas:
    """Quantized x async interaction (ISSUE 11 satellite): i8/f16
    `--delta-dtype` uploads through the async buffer — admission
    schema-checks the DEQUANTIZED image, the staleness-weighted drain
    merges it, and the committed model equals the spec-side
    recomputation from the same quantized bytes."""

    @pytest.mark.parametrize("dtype", ["f16", "i8"])
    def test_quantized_upload_staleness_drain(self, dtype):
        import dataclasses as _dc

        from bflc_demo_tpu.comm.identity import provision_wallets
        from bflc_demo_tpu.comm.ledger_service import (
            CoordinatorClient, LedgerServer)
        from bflc_demo_tpu.ledger.base import staleness_weight
        from bflc_demo_tpu.meshagg.engine import ENGINE
        from bflc_demo_tpu.utils.serialization import (
            dequantize_entries, pack_entries, pack_pytree,
            pack_quantized, unpack_pytree)

        cfg = _dc.replace(ACFG, client_num=8, needed_update_count=4,
                          async_buffer=2, max_staleness=4,
                          delta_dtype=dtype).validate()
        rng = np.random.default_rng(31)
        g0 = {"W": rng.standard_normal((6, 3)).astype(np.float32),
              "b": rng.standard_normal((3,)).astype(np.float32)}
        blob0 = pack_pytree(g0)
        wallets, _ = provision_wallets(8, b"async-quant-seed")
        srv = LedgerServer(cfg, blob0)
        srv.start()
        cl = CoordinatorClient(srv.host, srv.port)
        sent = {}
        try:
            from bflc_demo_tpu.comm.identity import _op_bytes

            def sign(w, kind, epoch, payload):
                return w.sign(_op_bytes(kind, w.address, epoch,
                                        payload)).hex()

            for w in wallets:
                assert cl.request("register", addr=w.address,
                                  pubkey=w.public_bytes.hex(),
                                  tag=sign(w, "register", 0, b""))["ok"]
            committee = set(cl.request("committee")["committee"])
            trainers = [w for w in wallets
                        if w.address not in committee]

            def aupload(i, w, base):
                delta = {"W": (rng.standard_normal((6, 3)) * 0.1
                               ).astype(np.float32),
                         "b": (rng.standard_normal((3,)) * 0.1
                               ).astype(np.float32)}
                blob = pack_quantized(delta, dtype)
                d = hashlib.sha256(blob).digest()
                sent[d] = (blob, 10 + i)
                payload = d + struct.pack("<qd", 10 + i, 1.0)
                return cl.request(
                    "aupload", addr=w.address, blob=blob, hash=d.hex(),
                    n=10 + i, cost=1.0, base_epoch=base,
                    tag=sign(w, "aupload", base, payload))

            # a delta whose quantized bytes hide a wrong-shaped leaf
            # still dies at admission (the check runs DEQUANTIZED)
            bad = pack_quantized({"W": np.zeros((2, 2), np.float32)},
                                 dtype)
            bd = hashlib.sha256(bad).digest()
            r = cl.request("aupload", addr=trainers[0].address,
                           blob=bad, hash=bd.hex(), n=5, cost=1.0,
                           base_epoch=0,
                           tag=sign(trainers[0], "aupload", 0,
                                    bd + struct.pack("<qd", 5, 1.0)))
            assert not r["ok"] and "mismatch" in r["error"], r

            # drain 1: two fresh quantized deltas -> epoch 1
            assert aupload(0, trainers[0], 0)["ok"]
            r = aupload(1, trainers[1], 0)
            assert r["ok"] and r["epoch"] == 1, r
            # drain 2: one stale (base 0 -> s=1) + one fresh upload
            assert aupload(2, trainers[2], 0)["ok"]
            au = cl.request("aupdates")
            assert au["updates"][0]["staleness"] == 1
            r = aupload(3, trainers[3], 1)
            assert r["ok"] and r["epoch"] == 2, r

            mr = cl.request("model")
            got = mr["hash"]

            # recompute both drains from the QUANTIZED bytes through
            # the one shared dequantizer + the reduction spec:
            # drain 1 = uploads 0,1 (staleness 0,0); drain 2 =
            # uploads 2,3 (staleness 1,0 — upload 2 trained on epoch 0
            # but was admitted at epoch 1)
            order = list(sent.values())
            model = unpack_pytree(blob0)    # canonical key paths
            for (a, b), stales in (((order[0], order[1]), (0, 0)),
                                   ((order[2], order[3]), (1, 0))):
                flats = [dequantize_entries(unpack_pytree(a[0])),
                         dequantize_entries(unpack_pytree(b[0]))]
                weights = [float(np.float32(
                    n * staleness_weight(s)))
                    for (_, n), s in zip((a, b), stales)]
                model = ENGINE.aggregate_flat(
                    model, flats, weights, [0, 1], cfg.learning_rate)
            want = hashlib.sha256(pack_entries(model)).hexdigest()
            assert got == want
        finally:
            cl.close()
            srv.close()


@pytest.mark.filterwarnings("ignore::UserWarning")
class TestAsyncChaosDrill:
    """Tier-1 async drill: a small fleet under a straggler delay window
    plus a client kill/restart — the chaos invariants (single certified
    history, monotone progress, acked-upload durability) must hold with
    the round barrier down."""

    def test_async_federation_under_chaos_keeps_invariants(
            self, tmp_path):
        from bflc_demo_tpu.chaos.schedule import (FaultEvent,
                                                  FaultSchedule,
                                                  WireWindow)
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        from bflc_demo_tpu.data import iid_shards, load_occupancy
        cfg = ProtocolConfig(client_num=4, comm_count=2,
                             aggregate_count=2, needed_update_count=2,
                             learning_rate=0.05, batch_size=32,
                             async_buffer=2,
                             max_staleness=8).validate()
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(np.asarray(xtr), np.asarray(ytr),
                            cfg.client_num)
        sched = FaultSchedule(13, duration_s=150.0, n_clients=4,
                              n_standbys=1, n_validators=2,
                              profile="light")
        sched.events = [FaultEvent(6.0, "kill", "client-3"),
                        FaultEvent(9.0, "restart", "client-3")]
        sched.wire_windows = {      # one persistent straggler
            "client-1": [WireWindow(0.0, 300.0, "delay", ("writer",),
                                    p=1.0, delay_ms=200.0)],
        }
        res = run_federated_processes(
            "make_softmax_regression", shards,
            (np.asarray(xte), np.asarray(yte)), cfg,
            rounds=4, standbys=1, bft_validators=2,
            chaos_schedule=sched, chaos_dir=str(tmp_path),
            timeout_s=300.0)
        assert res.rounds_completed >= 4
        rep = res.chaos_report
        assert rep is not None
        assert rep["violations"] == [], rep["violations"]
        v = rep["invariant_verdicts"]
        assert v["monotone_progress"] == "PASS"
        assert v["single_certified_history"] == "PASS"
        assert v["no_uncertified_bind"] == "PASS"
        assert v["acked_upload_durability"] == "PASS"
        assert rep["acked_uploads_checked"] > 0
        # the straggler never held a round open: rounds kept committing
        # while client-1's frames sat in the 200 ms delay window
        assert res.best_accuracy() > 0.5
