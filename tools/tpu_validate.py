"""On-TPU validation stages: run whenever the accelerator is reachable.

The CI suite pins CPU (tests/conftest.py) because multi-chip hardware isn't
guaranteed, so everything hardware-specific lives here: native Mosaic
compilation of the Pallas flash-attention kernel, correctness vs the einsum
core, and amortised timing at long sequence lengths.  Results append to
TPU_RESULTS.md and print as JSON for machine capture.

Usage:  python tools/tpu_validate.py [--rounds N] [--out TPU_RESULTS.md]

The kernel timing chains N applications inside ONE dispatch (lax.fori_loop)
so per-call tunnel/dispatch latency (~60 ms through the axon relay) doesn't
drown the kernel time — the same discipline bench.py uses for round times.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    platform = jax.devices()[0].platform
    if platform not in ("tpu",):
        print(json.dumps({"ok": False,
                          "error": f"no TPU (platform={platform})"}))
        return 1

    from bflc_demo_tpu.ops.pallas_attention import (flash_attention,
                                                    _reference_attention)

    rng = np.random.default_rng(0)
    rows = []

    def run_case(b, s, h, d, dtype, blk):
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
        mask = jnp.asarray(rng.random((b, s)) > 0.1)
        scale = 1.0 / np.sqrt(d)

        # correctness: one native-Mosaic call vs the einsum core
        out_p = jax.jit(lambda *a: flash_attention(*a, blk, blk, False))(
            q, k, v, mask)
        out_r = jax.jit(lambda *a: _reference_attention(*a, scale))(
            q, k, v, mask)
        err = float(jnp.max(jnp.abs(out_p.astype(jnp.float32)
                                    - out_r.astype(jnp.float32))))

        def amortised(fn):
            @jax.jit
            def many(q_):
                return jax.lax.fori_loop(
                    0, args.iters, lambda i, acc: fn(acc, k, v, mask), q_)
            many(q).block_until_ready()
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                many(q).block_until_ready()
                best = min(best, (time.perf_counter() - t0) / args.iters)
            return best

        tp = amortised(lambda q_, k_, v_, m_: flash_attention(
            q_, k_, v_, m_, blk, blk, False))
        tr = amortised(lambda q_, k_, v_, m_: _reference_attention(
            q_, k_, v_, m_, scale))
        rows.append({"b": b, "s": s, "h": h, "d": d,
                     "dtype": np.dtype(dtype).name, "block": blk,
                     "max_err": err, "pallas_ms": round(tp * 1e3, 2),
                     "einsum_ms": round(tr * 1e3, 2),
                     "speedup": round(tr / tp, 2)})
        print(json.dumps(rows[-1]), flush=True)

    run_case(2, 1024, 8, 64, jnp.float32, 128)
    run_case(2, 4096, 8, 64, jnp.bfloat16, 128)
    run_case(2, 8192, 8, 64, jnp.bfloat16, 128)

    ok = all(r["max_err"] < 5e-3 for r in rows)
    summary = {"ok": ok, "platform": platform,
               "device": str(jax.devices()[0]), "rows": rows}
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "a") as f:
            f.write(f"\n## tools/tpu_validate.py run "
                    f"({time.strftime('%Y-%m-%d %H:%M')})\n\n")
            f.write("| b | s | dtype | block | max_err | pallas ms | "
                    "einsum ms | speedup |\n|---|---|---|---|---|---|---|"
                    "---|\n")
            for r in rows:
                f.write(f"| {r['b']} | {r['s']} | {r['dtype']} | "
                        f"{r['block']} | {r['max_err']:.1e} | "
                        f"{r['pallas_ms']} | {r['einsum_ms']} | "
                        f"{r['speedup']}x |\n")
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
