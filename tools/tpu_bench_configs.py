"""Benchmark configs 1-5 on the real TPU chip (VERDICT round-1 item 8).

Runs each benchmark config's mesh-runtime geometry for a few rounds on the
accelerator, recording per-round times and best accuracy; appends a table
to TPU_RESULTS.md and prints one JSON line per config.  Geometries follow
each config's defaults; config 4 (ResNet-18 x 32 clients) relies on the
participation='active' / client_chunk / remat controls that keep it inside
a 16 GB v5e (eval/configs.py), and dataset size can be scaled down with
--n-data (configs 2-5; recorded in the artifact rather than hidden).

Usage: python tools/tpu_bench_configs.py [--rounds N] [--configs 2,3,4,5]
       [--n-data N] [--out TPU_RESULTS.md]

Each config runs in its own child process under a watchdog: one wedged
compile (the axon tunnel's failure mode) skips that config instead of
killing the sweep.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

CHILD_CODE = """
import json, time
import jax
from bflc_demo_tpu.utils.compile_cache import enable_persistent_cache
from bflc_demo_tpu.eval.configs import CONFIGS
from bflc_demo_tpu.eval.mfu import chip_peak_flops
enable_persistent_cache()
name, rounds, n_data = {name!r}, {rounds}, {n_data}
kw = dict(rounds=rounds, runtime="mesh", estimate_flops=True)
if n_data and name != "config1":     # config1 = fixed occupancy dataset
    kw["n_data"] = n_data
t0 = time.time()
res = CONFIGS[name].build(**kw)
wall = time.time() - t0
times = getattr(res, "round_times_s", None) or []
peak = chip_peak_flops()
mfu = (round(res.mfu(peak * res.n_devices), 5)
       if peak and res.flops_per_round else None)
print("RESULT " + json.dumps({{
    "config": name,
    "platform": jax.devices()[0].platform,
    "rounds": rounds,
    "wall_s": round(wall, 2),
    "min_round_s": round(min(times), 4) if times else None,
    "mean_round_s": round(sum(times) / len(times), 4) if times else None,
    "best_acc": round(res.best_accuracy(), 4),
    "flops_per_round": res.flops_per_round,
    "mfu": mfu,
    "n_data": n_data or "default",
}}))
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--n-data", type=int, default=0,
                    help="override dataset size (0 = config default)")
    ap.add_argument("--timeout", type=int, default=1200,
                    help="per-config watchdog seconds")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    rows = []
    for idx in args.configs.split(","):
        name = f"config{idx.strip()}"
        code = CHILD_CODE.format(name=name, rounds=args.rounds,
                                 n_data=args.n_data)
        try:
            t0 = time.time()
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=args.timeout,
                                  env=dict(os.environ))
            line = next((ln for ln in proc.stdout.splitlines()
                         if ln.startswith("RESULT ")), None)
            if proc.returncode == 0 and line:
                rows.append(json.loads(line[len("RESULT "):]))
            else:
                rows.append({"config": name, "error":
                             f"rc={proc.returncode}: "
                             f"{proc.stderr.strip()[-300:]}"})
        except subprocess.TimeoutExpired:
            rows.append({"config": name,
                         "error": f"timeout {args.timeout}s "
                                  f"(after {time.time() - t0:.0f}s)"})
        print(json.dumps(rows[-1]), flush=True)

    if args.out:
        with open(args.out, "a") as f:
            f.write(f"\n## tools/tpu_bench_configs.py run "
                    f"({time.strftime('%Y-%m-%d %H:%M')}, "
                    f"rounds={args.rounds})\n\n")
            f.write("| config | platform | min round s | mean round s | "
                    "best acc | MFU | note |\n|---|---|---|---|---|---|"
                    "---|\n")
            for r in rows:
                if "error" in r:
                    f.write(f"| {r['config']} | — | — | — | — | — | "
                            f"{r['error'][:80]} |\n")
                else:
                    f.write(f"| {r['config']} | {r['platform']} | "
                            f"{r['min_round_s']} | {r['mean_round_s']} | "
                            f"{r['best_acc']} | {r.get('mfu')} | "
                            f"n_data={r['n_data']} |\n")
    return 0 if all("error" not in r for r in rows) else 2


if __name__ == "__main__":
    sys.exit(main())
