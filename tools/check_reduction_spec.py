#!/usr/bin/env python
"""Differential checker for meshagg REDUCTION SPEC v1/v2.

The on-mesh aggregation engine (bflc_demo_tpu/meshagg) promises that its
compiled leg and its host-loop leg produce BYTE-IDENTICAL results — that
promise is what lets the certified model hash not depend on which leg
ran.  This tool is the standing proof obligation: randomized trees
(mixed leaf ranks, 0-d leaves, denormal and near-overflow magnitudes),
randomized weights (integer n_samples and FedBuff ``n/sqrt(1+s)``
staleness discounts), randomized selections (including empty and
full), and every decode image the data plane admits — delta dtypes
(plain f32, f16-decoded, i8-decoded) CROSSED with upload densities
(dense, 0.1 / 0.01) CROSSED with sparse codecs (`#topk` scatter
records, `#sketch` count-sketch tables, both through the one
sparse-encode -> quantize -> dequantize -> densify chain) — each
scenario reduced by BOTH legs and compared with exact byte equality,
plus the full ``aggregate_flat`` writer merge against the certified
canonical-bytes hash.  A closed-loop sweep
(`run_density_transition_differential`) additionally mixes pre/post
genome-op densities and codecs WITHIN one aggregation — the mid-run
knob change an adaptive fleet commits — and requires the writer and
validator re-derivation hashes to stay byte-identical across it.

REDUCTION SPEC v2 rides the same sweep: every scenario is additionally
reduced under ``reduce_blocks`` in {1, 2, 8, 64} (clamped to the
scenario's flattened param count) on the blocked host reference AND
the blocked mesh leg — all of them must be byte-identical to the v1
host loop, which is the spec's central claim (blocking the param axis
never moves a single accumulation out of slot order, so the committed
bytes cannot depend on the block count or the device count).

Runnable standalone (CI / a new platform's smoke test):

    python tools/check_reduction_spec.py [--trials 20] [--seed 0]
            [--max-n 64]

exit 0 = every scenario matched; exit 1 = divergence (prints the
scenario).  tests/test_meshagg.py invokes `run_differential` as a
tier-1 test with a reduced trial count.
"""

import argparse
import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _sparse_image(flat, density, codec):
    """The sparse encoder image of `flat` under the chosen codec —
    `#topk` scatter records or `#sketch` tables, the two wire forms
    `densify_entries` inverts."""
    from bflc_demo_tpu.utils.serialization import (sketch_entries,
                                                   sparsify_entries)
    if codec == "sketch":
        return sketch_entries(flat, density)
    return sparsify_entries(flat, density)


def _random_flat(rng, shapes, quant, density=1.0, codec="topk"):
    """One delta in a randomly chosen admitted decode image."""
    from bflc_demo_tpu.utils.serialization import (densify_entries,
                                                   dequantize_entries,
                                                   quantize_entries)
    flat = {}
    for k, shp in shapes.items():
        scale = 10.0 ** float(rng.integers(-8, 8))
        flat[k] = (rng.standard_normal(shp) * scale).astype(np.float32)
    if quant == "f32" and density >= 1.0:
        return flat
    # what admission/scoring/aggregation actually see for a sparse
    # and/or quantized upload: the ONE deterministic decode chain of
    # the exact bytes the client signed (sparsify/sketch runs BEFORE
    # quantize, densify AFTER dequantize — the wire order)
    return densify_entries(dequantize_entries(
        quantize_entries(_sparse_image(flat, density, codec), quant)))


def _scenario(rng, max_n):
    from bflc_demo_tpu.ledger.base import staleness_weight
    n = int(rng.integers(1, max_n + 1))
    n_leaves = int(rng.integers(1, 6))
    shapes = {}
    for j in range(n_leaves):
        rank = int(rng.integers(0, 3))
        shapes[f"/leaf{j}"] = tuple(
            int(d) for d in rng.integers(1, 9, size=rank))
    quant = ("f32", "f16", "i8")[int(rng.integers(0, 3))]
    density = (1.0, 0.1, 0.01)[int(rng.integers(0, 3))]
    codec = ("topk", "sketch")[int(rng.integers(0, 2))]
    deltas = [_random_flat(rng, shapes, quant, density, codec)
              for _ in range(n)]
    if deltas and "/leaf0" in deltas[0] and deltas[0]["/leaf0"].size:
        deltas[0]["/leaf0"].flat[0] = np.float32(1e-42)      # denormal
    # sync n_samples or async staleness-discounted weights
    if rng.integers(0, 2):
        weights = [float(rng.integers(1, 2000)) for _ in range(n)]
    else:
        weights = [float(np.float32(
            int(rng.integers(1, 2000))
            * staleness_weight(int(rng.integers(0, 20)))))
            for _ in range(n)]
    n_sel = int(rng.integers(0, n + 1))
    selected = sorted(int(i) for i in
                      rng.choice(n, size=n_sel, replace=False))
    lr = float(rng.random()) * 0.5
    g = {k: rng.standard_normal(shp).astype(np.float32)
         for k, shp in shapes.items()}
    return g, deltas, weights, selected, lr, quant, density, codec


BLOCKS_SWEEP = (1, 2, 8, 64)


def run_differential(trials: int = 20, seed: int = 0,
                     max_n: int = 64,
                     blocks_sweep=BLOCKS_SWEEP) -> dict:
    """Host leg vs compiled leg over `trials` randomized scenarios,
    then the same scenario under every ``reduce_blocks`` in
    `blocks_sweep` (v2 blocked host reference + blocked mesh leg, both
    vs the v1 host bytes).  Returns {"trials", "mismatches": [...],
    "compile_total"} — empty mismatches means the spec held."""
    from bflc_demo_tpu.meshagg import spec
    from bflc_demo_tpu.meshagg.engine import ENGINE
    from bflc_demo_tpu.utils.serialization import pack_entries

    rng = np.random.default_rng(seed)
    mismatches = []
    # arm the engine's one-time self-check so the summary line reports
    # a real verdict (force_leg below bypasses the policy that runs it)
    ENGINE.run_selfcheck()
    # the scenarios deliberately include magnitudes that overflow an
    # f16 decode image and drive inf/NaN through the reduction — both
    # legs must agree on those bytes too, so the warnings are noise
    with np.errstate(over="ignore", invalid="ignore"):
        for t in range(trials):
            g, deltas, weights, selected, lr, quant, density, codec = \
                _scenario(rng, max_n)
            keys = sorted(g.keys())
            w = spec.merge_weight_vector(weights, selected, len(deltas))
            wsum = max(float(w.sum()), 1e-12)
            host = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                       force_leg="host")
            mesh = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                       force_leg="mesh")
            bad = [k for k in keys if np.asarray(host[k]).tobytes()
                   != np.asarray(mesh[k]).tobytes()]
            # REDUCTION SPEC v2: the blocked host reference and the
            # blocked mesh leg, every geometry in the sweep, must
            # reproduce the v1 host bytes exactly
            p_total = sum(int(np.asarray(deltas[0][k]).size)
                          for k in keys) if deltas else 0
            for b in blocks_sweep:
                eff = min(int(b), max(p_total, 1))
                bh = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                         force_leg="host", blocks=eff)
                bm = ENGINE.weighted_sum(keys, deltas, w, wsum,
                                         force_leg="mesh", blocks=eff)
                bad.extend(f"#blocked-host-b{b}:{k}" for k in keys
                           if np.asarray(bh[k]).tobytes()
                           != np.asarray(host[k]).tobytes())
                bad.extend(f"#blocked-mesh-b{b}:{k}" for k in keys
                           if np.asarray(bm[k]).tobytes()
                           != np.asarray(host[k]).tobytes())
            # and the full writer merge: certified canonical bytes equal
            h_out = ENGINE.aggregate_flat(g, deltas, weights, selected,
                                          lr, force_leg="host")
            m_out = ENGINE.aggregate_flat(g, deltas, weights, selected,
                                          lr, force_leg="mesh")
            h_hash = hashlib.sha256(pack_entries(h_out)).digest()
            if h_hash != hashlib.sha256(pack_entries(m_out)).digest():
                bad.append("#aggregate_flat-hash")
            blk = min(int(blocks_sweep[-1]) if blocks_sweep else 1,
                      max(p_total, 1))
            b_out = ENGINE.aggregate_flat(g, deltas, weights, selected,
                                          lr, force_leg="mesh",
                                          blocks=blk)
            if h_hash != hashlib.sha256(pack_entries(b_out)).digest():
                bad.append("#aggregate_flat-blocked-hash")
            if bad:
                mismatches.append({
                    "trial": t, "n": len(deltas), "quant": quant,
                    "density": density, "codec": codec,
                    "selected": len(selected), "leaves": bad})
    return {"trials": trials, "seed": seed, "max_n": max_n,
            "mismatches": mismatches,
            "compile_total": ENGINE.compile_total,
            "report": ENGINE.report()}


def run_steady_state_check(repeats: int = 3, seed: int = 0,
                           max_n: int = 16) -> dict:
    """Steady-state recompile gate (device-plane observability): one
    FIXED scenario reduced `repeats` times through both legs and a
    blocked geometry.  The first pass may compile (geometry-keyed
    program-cache misses); every later pass must add ZERO fresh
    programs — the same guarantee the storm detector pages on when a
    live fleet violates it.  Returns {"repeats", "compile_totals",
    "fresh_after_warmup"}; the gate holds iff fresh_after_warmup == 0."""
    from bflc_demo_tpu.meshagg import spec
    from bflc_demo_tpu.meshagg.engine import ENGINE

    rng = np.random.default_rng(seed)
    g, deltas, weights, selected, lr, _, _, _ = _scenario(rng, max_n)
    keys = sorted(g.keys())
    w = spec.merge_weight_vector(weights, selected, len(deltas))
    wsum = max(float(w.sum()), 1e-12)
    p_total = sum(int(np.asarray(deltas[0][k]).size)
                  for k in keys) if deltas else 0
    blk = min(8, max(p_total, 1))
    totals = []
    with np.errstate(over="ignore", invalid="ignore"):
        for _ in range(max(int(repeats), 2)):
            ENGINE.weighted_sum(keys, deltas, w, wsum, force_leg="mesh")
            ENGINE.weighted_sum(keys, deltas, w, wsum, force_leg="mesh",
                                blocks=blk)
            ENGINE.aggregate_flat(g, deltas, weights, selected, lr,
                                  force_leg="mesh")
            totals.append(int(ENGINE.compile_total))
    return {"repeats": len(totals), "compile_totals": totals,
            "fresh_after_warmup": totals[-1] - totals[0]}


def run_rederive_differential(trials: int = 12, seed: int = 1,
                              max_n: int = 24,
                              n_validators: int = 4) -> dict:
    """The validator re-derivation leg (bflc_demo_tpu.rederive): for
    randomized trees x weights x selections x dtype x density, the
    WRITER path (decode every admitted blob, ENGINE.aggregate_flat,
    pack, hash) and the VALIDATOR path (`rederive_model_flat` over the
    raw wire blobs — selected only, zeros elsewhere) must produce
    byte-identical committed model hashes; and in shard mode every
    validator's re-derived leaves must equal the writer's with the
    shard union covering every leaf.  Each trial additionally runs the
    validator paths under a swept ``reduce_blocks`` geometry
    (REDUCTION SPEC v2) — the re-derived hashes must not move.  Empty
    `mismatches` = the plane can refuse on inequality without ever
    refusing an honest writer."""
    from bflc_demo_tpu.meshagg.engine import ENGINE
    from bflc_demo_tpu.rederive.core import (derive_leaves,
                                             rederive_model_flat)
    from bflc_demo_tpu.rederive.shards import leaf_shard
    from bflc_demo_tpu.utils.serialization import (densify_entries,
                                                   dequantize_entries,
                                                   pack_entries,
                                                   quantize_entries,
                                                   unpack_pytree)

    rng = np.random.default_rng(seed)
    mismatches = []
    with np.errstate(over="ignore", invalid="ignore"):
        for t in range(trials):
            g, _, weights, selected, lr, quant, density, codec = \
                _scenario(rng, max_n)
            n = len(weights)
            shapes = {k: np.asarray(v).shape for k, v in g.items()}
            # the raw WIRE blobs (what clients sign and upload)
            blobs = []
            for _ in range(n):
                flat = {k: (rng.standard_normal(shp)
                            * 10.0 ** float(rng.integers(-6, 6))
                            ).astype(np.float32)
                        for k, shp in shapes.items()}
                blobs.append(pack_entries(quantize_entries(
                    _sparse_image(flat, density, codec), quant)))
            prev_blob = pack_entries(g)
            # writer path: decode all, one engine merge, pack, hash
            decoded = [densify_entries(dequantize_entries(
                           unpack_pytree(b))) for b in blobs]
            w_out = ENGINE.aggregate_flat(g, decoded, weights, selected,
                                          lr)
            w_hash = hashlib.sha256(pack_entries(w_out)).digest()
            # validator FULL path over raw blobs (selected only)
            v_out = rederive_model_flat(prev_blob, blobs, weights,
                                        selected, lr,
                                        sparse=density < 1.0)
            v_hash = hashlib.sha256(pack_entries(v_out)).digest()
            bad = []
            if v_hash != w_hash:
                bad.append("#full-hash")
            # v2: the same re-derivation under a blocked geometry —
            # byte-identical by the spec's construction
            blk = int(BLOCKS_SWEEP[t % len(BLOCKS_SWEEP)])
            p_total = sum(int(np.asarray(v).size) for v in g.values())
            blk = min(blk, max(p_total, 1))
            vb_out = rederive_model_flat(prev_blob, blobs, weights,
                                         selected, lr,
                                         sparse=density < 1.0,
                                         blocks=blk)
            if hashlib.sha256(
                    pack_entries(vb_out)).digest() != w_hash:
                bad.append(f"#full-blocked-hash-b{blk}")
            # validator SHARD path: per-validator leaves + union cover
            keys = sorted(g.keys())
            epoch = int(rng.integers(0, 50))
            covered = set()
            sel = set(selected)
            flats = [decoded[i] if i in sel else None for i in range(n)]
            for v in range(n_validators):
                mine = leaf_shard(keys, v, n_validators, epoch)
                covered.update(mine)
                got = derive_leaves(g, flats, weights, selected, lr,
                                    mine, blocks=blk)
                for k in mine:
                    if np.asarray(got[k]).tobytes() != \
                            np.asarray(w_out[k]).tobytes():
                        bad.append(f"#shard-v{v}:{k}")
            if covered != set(keys):
                bad.append("#shard-coverage")
            if bad:
                mismatches.append({"trial": t, "n": n, "quant": quant,
                                   "density": density, "codec": codec,
                                   "leaves": bad})
    return {"trials": trials, "seed": seed, "max_n": max_n,
            "n_validators": n_validators, "mismatches": mismatches}


def run_density_transition_differential(trials: int = 8, seed: int = 2,
                                        max_n: int = 24) -> dict:
    """The mid-run knob-change differential (closed-loop compression):
    a certified genome-update op can retune `delta_density` BETWEEN a
    round's uploads being encoded and admitted, so one aggregation may
    legitimately hold blobs encoded at DIFFERENT densities (and, on a
    codec change, different sparse record types).  Both consumers —
    the WRITER path (decode every blob through the one inverse, one
    engine merge) and the VALIDATOR path (`rederive_model_flat` over
    the raw wire blobs, plain and blocked) — are density-agnostic at
    admission by construction; this check is the standing proof: mixed
    pre/post-transition blobs must re-derive to byte-identical
    committed model hashes.  Empty `mismatches` = an adaptive fleet
    never needs a flag day to move the knob."""
    from bflc_demo_tpu.meshagg.engine import ENGINE
    from bflc_demo_tpu.rederive.core import rederive_model_flat
    from bflc_demo_tpu.utils.serialization import (densify_entries,
                                                   dequantize_entries,
                                                   pack_entries,
                                                   quantize_entries,
                                                   unpack_pytree)

    rng = np.random.default_rng(seed)
    mismatches = []
    with np.errstate(over="ignore", invalid="ignore"):
        for t in range(trials):
            g, _, weights, selected, lr, quant, _, _ = \
                _scenario(rng, max_n)
            n = len(weights)
            shapes = {k: np.asarray(v).shape for k, v in g.items()}
            # the knob transition: uploads encoded before the genome op
            # ride the old density/codec, uploads after ride the new
            d_pre = (1.0, 0.1)[int(rng.integers(0, 2))]
            d_post = (0.1, 0.05, 0.01)[int(rng.integers(0, 3))]
            c_pre = ("topk", "sketch")[int(rng.integers(0, 2))]
            c_post = ("topk", "sketch")[int(rng.integers(0, 2))]
            cut = int(rng.integers(0, n + 1))
            blobs = []
            for i in range(n):
                flat = {k: (rng.standard_normal(shp)
                            * 10.0 ** float(rng.integers(-6, 6))
                            ).astype(np.float32)
                        for k, shp in shapes.items()}
                d, c = (d_pre, c_pre) if i < cut else (d_post, c_post)
                blobs.append(pack_entries(quantize_entries(
                    _sparse_image(flat, d, c), quant)))
            prev_blob = pack_entries(g)
            decoded = [densify_entries(dequantize_entries(
                           unpack_pytree(b))) for b in blobs]
            w_out = ENGINE.aggregate_flat(g, decoded, weights, selected,
                                          lr)
            w_hash = hashlib.sha256(pack_entries(w_out)).digest()
            bad = []
            v_out = rederive_model_flat(prev_blob, blobs, weights,
                                        selected, lr, sparse=True)
            if hashlib.sha256(
                    pack_entries(v_out)).digest() != w_hash:
                bad.append("#transition-full-hash")
            p_total = sum(int(np.asarray(v).size) for v in g.values())
            blk = min(int(BLOCKS_SWEEP[t % len(BLOCKS_SWEEP)]),
                      max(p_total, 1))
            vb_out = rederive_model_flat(prev_blob, blobs, weights,
                                         selected, lr, sparse=True,
                                         blocks=blk)
            if hashlib.sha256(
                    pack_entries(vb_out)).digest() != w_hash:
                bad.append(f"#transition-blocked-hash-b{blk}")
            if bad:
                mismatches.append({
                    "trial": t, "n": n, "quant": quant, "cut": cut,
                    "pre": [d_pre, c_pre], "post": [d_post, c_post],
                    "leaves": bad})
    return {"trials": trials, "seed": seed, "max_n": max_n,
            "mismatches": mismatches}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-n", type=int, default=64)
    args = ap.parse_args(argv)
    out = run_differential(args.trials, args.seed, args.max_n)
    print(f"reduction spec differential: {out['trials']} trials, "
          f"blocks sweep {list(BLOCKS_SWEEP)}, "
          f"{out['compile_total']} programs compiled, "
          f"selfcheck={out['report']['selfcheck']}")
    if out["mismatches"]:
        for m in out["mismatches"]:
            print(f"  DIVERGED: {m}")
        print("FAIL: host and mesh legs are not byte-identical on "
              "this platform — certified aggregation must stay on the "
              "host loop (BFLC_MESH_AGG_LEGACY=1) until resolved")
        return 1
    print("OK: host-loop, mesh, and blocked (v2) legs byte-identical "
          "on every scenario")
    ss = run_steady_state_check(seed=args.seed)
    print(f"steady-state recompile gate: {ss['repeats']} repeats, "
          f"compile totals {ss['compile_totals']}, "
          f"fresh after warmup {ss['fresh_after_warmup']}")
    if ss["fresh_after_warmup"]:
        print("FAIL: a repeated identical scenario compiled fresh XLA "
              "programs after its warmup pass — the geometry-keyed "
              "program cache is not holding (a live fleet would page "
              "via the recompile-storm detector)")
        return 1
    red = run_rederive_differential(max(args.trials // 2, 6), args.seed)
    print(f"rederive differential: {red['trials']} trials x "
          f"{red['n_validators']} validators")
    if red["mismatches"]:
        for m in red["mismatches"]:
            print(f"  DIVERGED: {m}")
        print("FAIL: validator re-derivation path is not "
              "byte-identical to the writer path — the rederive plane "
              "must stay off (--rederive off) until resolved")
        return 1
    print("OK: writer path and validator re-derivation path "
          "byte-identical on every scenario")
    dt = run_density_transition_differential(max(args.trials // 2, 6),
                                             args.seed + 2)
    print(f"density-transition differential: {dt['trials']} trials "
          f"(mixed pre/post-genome densities and codecs per round)")
    if dt["mismatches"]:
        for m in dt["mismatches"]:
            print(f"  DIVERGED: {m}")
        print("FAIL: a mid-run density/codec change produced "
              "writer-vs-validator hash divergence — the adaptive "
              "genome loop must stay disarmed (adapt_every=0) until "
              "resolved")
        return 1
    print("OK: writer and validator paths byte-identical across "
          "mid-run density/codec transitions")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
