#!/usr/bin/env python
"""check_tier1_budget: enforce the tier-1 suite's 870 s budget and the
slow-marking policy from a pytest log.

The tier-1 suite runs `-m 'not slow'` (ROADMAP.md), so EVERY test in
its log is by construction unmarked — and the suite has crept past
700 s twice, each time fixed by manually hunting the offender and
demoting it to `slow`.  This tool makes the policy enforceable: feed
it a pytest log produced with ``--durations=0`` and it

- reports the per-test duration table (call + setup + teardown summed
  per nodeid, slowest first);
- totals them against the tier-1 budget (default 870 s) with the
  headroom fraction;
- FAILS (exit 1) when any test exceeds --limit seconds (default 30) —
  the signal that it must either get faster or take the `slow` mark
  (with a docstring rationale, per the established policy).

Usage:
    pytest tests/ -q -m 'not slow' --durations=0 2>&1 | tee t1.log
    python tools/check_tier1_budget.py t1.log [--limit 30]
        [--budget 870] [--top 15]
"""

import argparse
import json
import re
import sys
from typing import Dict, List, Tuple

# `--durations` lines: "  12.34s call     tests/test_x.py::TestY::test_z"
_DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)")
# the summary wall line: "549 passed, 2 skipped in 389.12s"
_SUMMARY_RE = re.compile(
    r"(\d+) passed.*?in (\d+(?:\.\d+)?)s")


def parse_log(text: str) -> Tuple[Dict[str, float], float, int]:
    """({nodeid: summed seconds}, summary wall seconds or 0, passed)."""
    per_test: Dict[str, float] = {}
    wall, passed = 0.0, 0
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if m:
            dur, _phase, nodeid = m.groups()
            per_test[nodeid] = per_test.get(nodeid, 0.0) + float(dur)
            continue
        s = _SUMMARY_RE.search(line)
        if s:
            passed, wall = int(s.group(1)), float(s.group(2))
    return per_test, wall, passed


def check(per_test: Dict[str, float], wall: float, *,
          budget: float = 870.0, limit: float = 30.0) -> dict:
    ranked: List[Tuple[str, float]] = sorted(
        per_test.items(), key=lambda kv: -kv[1])
    total = sum(per_test.values())
    over = [{"test": t, "seconds": round(s, 2)}
            for t, s in ranked if s > limit]
    return {
        "tests": len(per_test),
        "sum_durations_s": round(total, 2),
        "summary_wall_s": wall,
        "budget_s": budget,
        "budget_used_frac": round((wall or total) / budget, 3)
        if budget else None,
        "limit_s": limit,
        "over_limit": over,
        "ranked": [{"test": t, "seconds": round(s, 2)}
                   for t, s in ranked],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("log", help="pytest log with --durations=0 output")
    ap.add_argument("--budget", type=float, default=870.0,
                    help="tier-1 wall budget in seconds (default 870)")
    ap.add_argument("--limit", type=float, default=30.0,
                    help="per-unmarked-test ceiling in seconds "
                         "(default 30)")
    ap.add_argument("--top", type=int, default=15,
                    help="slowest tests to print (default 15)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    try:
        with open(args.log) as fh:
            text = fh.read()
    except OSError as e:
        print(f"cannot read {args.log}: {e}", file=sys.stderr)
        return 2
    per_test, wall, passed = parse_log(text)
    if not per_test:
        print(f"{args.log}: no --durations lines found — run pytest "
              f"with --durations=0", file=sys.stderr)
        return 2
    report = check(per_test, wall, budget=args.budget,
                   limit=args.limit)
    if args.json:
        report["ranked"] = report["ranked"][: args.top]
        print(json.dumps(report, indent=2))
    else:
        print(f"{report['tests']} tests, "
              f"sum {report['sum_durations_s']:.1f}s"
              + (f", suite wall {wall:.1f}s" if wall else "")
              + f" — {report['budget_used_frac']:.0%} of the "
                f"{args.budget:.0f}s tier-1 budget")
        print(f"\nslowest {min(args.top, len(report['ranked']))}:")
        for row in report["ranked"][: args.top]:
            flag = "  << OVER LIMIT" if row["seconds"] > args.limit \
                else ""
            print(f"  {row['seconds']:8.2f}s  {row['test']}{flag}")
        if report["over_limit"]:
            print(f"\nFAIL: {len(report['over_limit'])} unmarked "
                  f"test(s) exceed the {args.limit:.0f}s ceiling — "
                  f"speed them up or demote to @pytest.mark.slow "
                  f"with a docstring rationale:")
            for row in report["over_limit"]:
                print(f"  {row['seconds']:8.2f}s  {row['test']}")
        else:
            print(f"\nOK: no unmarked test over {args.limit:.0f}s")
    return 1 if report["over_limit"] else 0


if __name__ == "__main__":
    sys.exit(main())
