#!/usr/bin/env python
"""fleet_top: render a telemetry run artifact (obs.collector timeline).

Three modes over a `metrics.jsonl` written by the FleetCollector (a
federation run with `telemetry_dir=...`, `tools/chaos_soak.py`, or the
federation benchmark):

    --once      one per-role table from the newest scrape, then exit;
    --timeline  the post-mortem: fault events interleaved with each
                scrape's key samples on one time-ordered stream (the
                fault -> metric causality view);
    (default)   live top: follow the file and re-render every --refresh
                seconds until interrupted.

Usage:
    python tools/fleet_top.py <metrics.jsonl> [--once | --timeline]
    python tools/fleet_top.py <telemetry_dir>        # finds metrics.jsonl
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bflc_demo_tpu.obs.collector import load_timeline  # noqa: E402
from bflc_demo_tpu.obs.metrics import (hist_quantile,  # noqa: E402
                                       merge_hist_samples)


def _hist_stats(sample):
    """(count, mean, p50) from one cumulative-bucket hist sample."""
    count = sample.get("count", 0)
    if not count:
        return 0, 0.0, 0.0
    return count, sample.get("sum", 0.0) / count, \
        hist_quantile(sample, 0.5)


def _metric(snapshot, name):
    return ((snapshot.get("metrics") or {}).get(name) or {}).get(
        "samples", [])


def _gauge_value(snapshot, name, default=None):
    s = _metric(snapshot, name)
    return s[0]["value"] if s else default


def _sum_counter(snapshot, name, **want):
    total = 0.0
    for s in _metric(snapshot, name):
        lab = s.get("labels", {})
        if all(lab.get(k) == v for k, v in want.items()):
            total += s.get("value", 0.0)
    return total


def _merged_hist(snapshot, name, **want):
    count, tot = 0, 0.0
    for s in _metric(snapshot, name):
        lab = s.get("labels", {})
        if all(lab.get(k) == v for k, v in want.items()):
            count += s.get("count", 0)
            tot += s.get("sum", 0.0)
    return count, (tot / count if count else 0.0)


def _fmt_q(v, scale=1.0, unit=""):
    return "inf" if v == float("inf") else f"{v * scale:.0f}{unit}"


def _merged_tail(snapshot, name, scale=1.0, unit="", **want):
    """'p50/p95/p99' string from the merged histogram, or None when
    empty — tails, not means, for the straggler/staleness panels
    (upper-bucket-bound estimates, obs.metrics.hist_quantile)."""
    samples = [s for s in _metric(snapshot, name)
               if all(s.get("labels", {}).get(k) == v
                      for k, v in want.items())]
    merged = merge_hist_samples(samples)
    if not merged["count"]:
        return None
    return "/".join(_fmt_q(hist_quantile(merged, q), scale, unit)
                    for q in (0.5, 0.95, 0.99))


def _health_cell(snap):
    """The model-quality health panel (obs.health) for any role that
    runs a monitor — the root writer AND every cell aggregator
    (member-level verdicts live at the cell; the root only sees the
    merged partial).  None until a verdict exists."""
    hv = _gauge_value(snap, "health_verdict")
    if hv is None:
        return None
    crit = _sum_counter(snap, "health_verdicts_total", level="crit")
    warn = _sum_counter(snap, "health_verdicts_total", level="warn")
    upd = _gauge_value(snap, "global_update_norm", 0.0)
    dis = _gauge_value(snap, "committee_score_disagreement", 0.0)
    word = ("OK", "WARN", "CRIT")[min(int(hv), 2)]
    flagged = int(_gauge_value(snap, "health_flagged_senders", 0))
    return (f"health {word}  flagged {flagged}  "
            f"upd {upd:.3g}  disagree {dis:.3f}  "
            f"w/c {warn:.0f}/{crit:.0f}")


def _sparse_cell(snap):
    """The sparse-upload panel (--delta-density) for any admitting role
    — the writer and every cell aggregator: protocol density plus the
    per-blob densify decode cost.  None on a dense fleet."""
    dens = _gauge_value(snap, "delta_density")
    if dens is None or dens >= 1.0:
        return None
    n_sd, m_sd = _merged_hist(snap, "sparse_decode_seconds")
    return (f"sparse d={dens:g}"
            + (f"  decode {n_sd}x{m_sd * 1e3:.1f}ms" if n_sd else ""))


def _adaptive_cell(snap):
    """The closed-loop compression panel (--adapt-every): which
    certified genome epoch pins the current effective knobs, how many
    genome-update ops this chain has applied, and the effective
    staleness bound (async fleets).  None when the loop is disarmed —
    the gauge only exists on adapt-armed writers."""
    ge = _gauge_value(snap, "genome_epoch")
    if ge is None:
        return None
    n = _sum_counter(snap, "genome_updates_total")
    stale = _gauge_value(snap, "effective_staleness", 0)
    cell = ("adapt genome@-" if ge < 0 else f"adapt genome@{int(ge)}")
    cell += f"  updates {n:.0f}"
    if stale:
        cell += f"  stale<={int(stale)}"
    return cell


def _role_row(role, snap):
    """One table row: the per-role-class key numbers."""
    costs = snap.get("trace_costs") or {}
    cells = [f"{role:<14}"]
    if role.startswith("client"):
        n_tr, m_tr = _merged_hist(snap, "client_phase_seconds",
                                  phase="train")
        n_up, m_up = _merged_hist(snap, "client_phase_seconds",
                                  phase="upload")
        n_sc, m_sc = _merged_hist(snap, "client_phase_seconds",
                                  phase="score")
        cells.append(f"train {n_tr}x{m_tr * 1e3:6.0f}ms  "
                     f"upload {n_up}x{m_up * 1e3:6.0f}ms  "
                     f"score {n_sc}x{m_sc * 1e3:6.0f}ms")
        # data-plane read routing (PR 5): where this client's model/blob
        # bytes came from, and the content-addressed cache's hit ratio
        reads = {src: _sum_counter(snap, "dataplane_reads_total",
                                   source=src)
                 for src in ("cache", "replica", "writer")}
        hits = _sum_counter(snap, "dataplane_cache_events_total",
                            event="hit")
        misses = _sum_counter(snap, "dataplane_cache_events_total",
                              event="miss")
        fb = _sum_counter(snap, "dataplane_blob_fallback_total")
        if any(reads.values()):
            cells.append(
                f"reads {reads['cache']:.0f}c/{reads['replica']:.0f}r/"
                f"{reads['writer']:.0f}w"
                + (f"  hit {hits / (hits + misses):.0%}"
                   if hits + misses else "")
                + (f"  fb {fb:.0f}" if fb else ""))
        # sparse upload deltas (--delta-density): client-side top-k
        # encode cost per upload
        n_se, m_se = _merged_hist(snap, "sparse_encode_seconds")
        if n_se:
            cells.append(f"sparse-enc {n_se}x{m_se * 1e3:.1f}ms")
    elif role.startswith("validator"):
        n_b, m_b = _merged_hist(snap, "vote_latency_seconds",
                                kind="batch")
        n_s, m_s = _merged_hist(snap, "vote_latency_seconds",
                                kind="single")
        rep = _sum_counter(snap, "repair_events_total")
        ab = _sum_counter(snap, "abandon_events_total")
        log = _gauge_value(snap, "validator_log_size", 0)
        cells.append(f"log {int(log):>5}  votes {n_b}b/{n_s}s "
                     f"({m_b * 1e3:.1f}/{m_s * 1e3:.1f}ms)  "
                     f"repairs {rep:.0f}  abandons {ab:.0f}")
        # validator re-derivation plane (bflc_demo_tpu.rederive): how
        # many commits this validator re-derived, the mean cost, and
        # the degrade/refusal counters an operator pages on
        n_rd, m_rd = _merged_hist(snap, "rederive_seconds")
        if n_rd:
            skip = _sum_counter(snap, "rederive_skipped_total")
            ref = _sum_counter(snap, "rederive_refusals_total")
            cells.append(f"rederive {n_rd}x{m_rd * 1e3:.1f}ms  "
                         f"skip {skip:.0f}  refuse {ref:.0f}")
    elif role.startswith("cell"):
        # hierarchical cell tier (bflc_demo_tpu.hier): the aggregator is
        # a LedgerServer for its members, so it also has the writer-class
        # gauges; the cell-specific axes are admitted count, partial-sum
        # latency, and the cell-aggregate op's root (certify) round-trip
        rnd = _gauge_value(snap, "round", 0)
        adm = _gauge_value(snap, "cell_admitted", 0)
        n_p, m_p = _merged_hist(snap, "cell_partial_seconds")
        n_a, m_a = _merged_hist(snap, "cell_root_ack_seconds")
        cells.append(f"round {int(rnd):>3}  admitted {int(adm):>3}  "
                     f"partial {n_p}x{m_p * 1e3:5.1f}ms  "
                     f"root-certify {n_a}x{m_a * 1e3:6.1f}ms")
        # sparse bridge (--delta-density): member-blob decode cost and
        # the density this cell re-sparsifies its partial at
        sp = _sparse_cell(snap)
        if sp is not None:
            cells.append(sp)
        # member-level health verdicts live HERE, not at the root
        hc = _health_cell(snap)
        if hc is not None:
            cells.append(hc)
    elif role.startswith("standby"):
        applied = _gauge_value(snap, "standby_applied_ops", 0)
        lag = _gauge_value(snap, "standby_ack_lag_ops", 0)
        n_m, m_m = _merged_hist(snap, "standby_mirror_latency_seconds")
        promos = _sum_counter(snap, "standby_promotions_total")
        cells.append(f"applied {int(applied):>5}  ack-lag {int(lag)}  "
                     f"mirror {n_m}x{m_m * 1e3:.1f}ms  "
                     f"promotions {promos:.0f}")
        # certified-snapshot state-sync (PR 7): rejoins that installed a
        # checkpoint instead of replaying, and the mirrored ops GC'd
        # behind streamed snapshot ops
        n_ss, m_ss = _merged_hist(snap, "state_sync_seconds")
        refused = _sum_counter(snap, "state_syncs_total",
                               outcome="refused")
        gc = _sum_counter(snap, "standby_gc_ops_total")
        if n_ss or refused or gc:
            cells.append(f"state-sync {n_ss}x{m_ss * 1e3:.0f}ms"
                         + (f"  refused {refused:.0f}" if refused else "")
                         + (f"  gc {gc:.0f}ops" if gc else ""))
    else:                               # writer / executor
        rnd = _gauge_value(snap, "round", 0)
        backlog = _gauge_value(snap, "uncertified_backlog", 0)
        n_c, m_c = _merged_hist(snap, "certify_latency_seconds")
        n_bt, m_bt = _merged_hist(snap, "cert_batch_size")
        ct = _merged_tail(snap, "certify_latency_seconds", scale=1e3,
                          unit="ms")
        cells.append(f"round {int(rnd):>3}  backlog {int(backlog):>3}  "
                     f"certify {n_c}x{m_c * 1e3:6.1f}ms"
                     + (f" (p50/95/99 {ct})" if n_c else "")
                     + f"  batch-mean {m_bt:4.1f}")
        # certified snapshots + compaction (PR 7): checkpoint freshness
        # and the bounded-log evidence (GC'd prefix depth + reclaimed ops)
        age = _gauge_value(snap, "snapshot_age_rounds")
        if age is not None and age >= 0:
            sbytes = _gauge_value(snap, "snapshot_bytes", 0)
            base = _gauge_value(snap, "log_base", 0)
            gc = _sum_counter(snap, "ledger_gc_ops_total")
            cells.append(f"snap age {int(age)}r/"
                         f"{sbytes / 1e6:.2f}MB  base {int(base)}  "
                         f"gc {gc:.0f}ops")
        # straggler panel: admission lag behind each round's first
        # upload — the TAIL is the story (p50/p95/p99, not a mean)
        lag = _merged_tail(snap, "upload_lag_seconds", scale=1e3,
                           unit="ms")
        if lag is not None:
            cells.append(f"lag p50/95/99 {lag}")
        # async buffered aggregation (--async-buffer K): buffer
        # occupancy, admitted-staleness tail, aggregations
        aggs = _sum_counter(snap, "async_aggregations_total")
        st = _merged_tail(snap, "async_admitted_staleness", unit="ep")
        if aggs or st is not None:
            depth = _gauge_value(snap, "async_buffer_depth", 0)
            cells.append(f"async buf {int(depth)}  "
                         f"staleness p50/95/99 {st or '-'}  "
                         f"aggs {aggs:.0f}")
        # async committee re-election (--reseat-every R): seated size
        # + reseats applied; the seat NAMES render in the committee
        # panel (writer flight events carry them)
        reseats = _sum_counter(snap, "committee_reseats_total")
        if reseats:
            csize = _gauge_value(snap, "committee_size", 0)
            cells.append(f"committee {int(csize)} seats  "
                         f"reseats {reseats:.0f}")
        # sparse upload deltas (--delta-density): protocol density +
        # writer-side densify decode cost per admitted blob
        sp = _sparse_cell(snap)
        if sp is not None:
            cells.append(sp)
        # closed-loop compression (--adapt-every, ledger.OP_GENOME):
        # the LIVE effective knobs the certified schedule pins right
        # now — the density above is already the effective one; this
        # names the schedule driving it (last genome epoch + applied
        # count + the staleness bound on async fleets)
        ad = _adaptive_cell(snap)
        if ad is not None:
            cells.append(ad)
        # model-quality health plane (obs.health): last round's
        # verdict, flagged senders, update norm, committee disagreement
        hc = _health_cell(snap)
        if hc is not None:
            cells.append(hc)
        # on-mesh batched aggregation (meshagg): per-leg reduction
        # calls + latency, stacked-batch size, and programs compiled
        # (one cache miss per round geometry)
        n_mm, m_mm = _merged_hist(snap, "mesh_agg_seconds",
                                  kernel="reduce", leg="mesh")
        n_mh, m_mh = _merged_hist(snap, "mesh_agg_seconds",
                                  kernel="reduce", leg="host")
        n_ml, m_ml = _merged_hist(snap, "mesh_agg_seconds",
                                  kernel="reduce", leg="legacy")
        # REDUCTION SPEC v2: the blocked leg reports under its own
        # label, and the genome's block geometry rides the gauge
        n_bk, m_bk = _merged_hist(snap, "mesh_agg_seconds",
                                  kernel="reduce", leg="blocked")
        if n_mm or n_mh or n_ml or n_bk:
            nb, mb = _merged_hist(snap, "mesh_agg_batch_size")
            comp = _sum_counter(snap, "mesh_agg_compile_total")
            n_h = n_mh + n_ml
            m_h = ((m_mh * n_mh + m_ml * n_ml) / n_h) if n_h else 0.0
            cell = (f"mesh-agg jit {n_mm}x{m_mm * 1e3:.1f}ms / "
                    f"host {n_h}x{m_h * 1e3:.1f}ms")
            if n_bk:
                blk = int(_gauge_value(snap, "mesh_agg_blocks", 0))
                cell += (f" / blk{blk} {n_bk}x{m_bk * 1e3:.1f}ms")
            cells.append(cell + f"  batch~{mb:.0f}  "
                         f"compiles {comp:.0f}")
    # device plane (obs.device): per-role XLA compile/cache attribution
    # and the process memory watermark — any role that traced a jit
    # boundary gets the cell; quiet otherwise (BFLC_DEVICE_OBS=0 pin)
    dcomp = _sum_counter(snap, "device_compile_total")
    dhits = _sum_counter(snap, "device_program_cache_total", event="hit")
    dmiss = _sum_counter(snap, "device_program_cache_total",
                         event="miss")
    if dcomp or dhits or dmiss:
        n_ex, m_ex = _merged_hist(snap, "device_execute_seconds")
        cell = f"xla compiles {dcomp:.0f}"
        if dhits + dmiss:
            cell += f"  cache {dhits / (dhits + dmiss):.0%}"
        if n_ex:
            cell += f"  exec {n_ex}x{m_ex * 1e3:.1f}ms"
        cells.append(cell)
    peak = max((s.get("value", 0.0)
                for s in _metric(snap, "device_mem_peak_bytes")),
               default=0.0)
    if peak:
        lim = max((s.get("value", 0.0)
                   for s in _metric(snap, "device_mem_limit_bytes")),
                  default=0.0)
        cells.append(f"mem peak {peak / 1e6:.0f}MB"
                     + (f" ({peak / lim:.0%} of ceiling)"
                        if lim else ""))
    wire_in = costs.get("wire.bytes_in", 0)
    wire_out = costs.get("wire.bytes_out", 0)
    if wire_in or wire_out:
        cells.append(f"wire {wire_in / 1e6:6.2f}/{wire_out / 1e6:6.2f} MB")
    bin_n = _sum_counter(snap, "wire_frames_total", kind="bin")
    json_n = _sum_counter(snap, "wire_frames_total", kind="json")
    zip_n = _sum_counter(snap, "wire_frames_total", kind="zip")
    if bin_n or json_n or zip_n:
        cells.append(f"frames {bin_n:.0f}bin/{json_n:.0f}json/"
                     f"{zip_n:.0f}zip")
    zraw = _sum_counter(snap, "wire_zip_bytes_total", which="raw")
    zwire = _sum_counter(snap, "wire_zip_bytes_total", which="wire")
    if zwire:
        cells.append(f"zip {zraw / zwire:.2f}x")
    served = _sum_counter(snap, "readfan_requests_total")
    if served:
        cells.append(f"served {served:.0f} reads")
    return "  ".join(cells)


def _slo_panel(art_dir: str) -> list:
    """SLO plane rows (obs.slo): per-objective burn state off the
    newest scrape's writer gauges is not available here (the engine
    runs driver-side), so the panel renders the durable artifact —
    alerts.jsonl — which is exactly what an operator pages on.  Empty
    when the plane is unarmed or quiet."""
    if not art_dir:
        return []
    path = os.path.join(art_dir, "alerts.jsonl")
    if not os.path.exists(path):
        return []
    from bflc_demo_tpu.obs.slo import load_alerts
    alerts = load_alerts(path)
    if not alerts:
        return []
    lines = [f"SLO alerts ({len(alerts)}; tools/obs_query.py --slo "
             f"<name> for context):"]
    for a in alerts[-8:]:
        lines.append(
            f"  round {a.get('epoch')}: {a.get('slo')} "
            f"{a.get('signal')}={a.get('value')} vs {a.get('op')} "
            f"{a.get('bound')} (burn {a.get('burn_fast')}/"
            f"{a.get('burn_slow')})")
    return lines


def _reseat_events(art_dir: str) -> list:
    """``committee_reseat`` flight events off the writer's flight dump
    (async re-election, ProtocolConfig.async_reseat_every) — the only
    artifact that names the SEATS, not just the count."""
    if not art_dir:
        return []
    path = os.path.join(art_dir, "writer.flight.jsonl")
    if not os.path.exists(path):
        return []
    try:
        from bflc_demo_tpu.obs.flight import load_flight
        evs = load_flight(path).get("events", [])
    except (OSError, ValueError):
        return []
    return [e for e in evs if isinstance(e, dict)
            and e.get("name") == "committee_reseat"]


def _genome_events(art_dir: str) -> list:
    """``genome_update`` flight events off the writer's flight dump
    (closed-loop compression, ledger.OP_GENOME) — the artifact that
    names each certified knob transition and the telemetry the fixed
    rule decided on."""
    if not art_dir:
        return []
    path = os.path.join(art_dir, "writer.flight.jsonl")
    if not os.path.exists(path):
        return []
    try:
        from bflc_demo_tpu.obs.flight import load_flight
        evs = load_flight(path).get("events", [])
    except (OSError, ValueError):
        return []
    return [e for e in evs if isinstance(e, dict)
            and e.get("name") == "genome_update"]


def _committee_panel(art_dir: str) -> list:
    """Current seating per the newest reseat event; empty on frozen-
    committee (R=0 / sync) fleets."""
    evs = _reseat_events(art_dir)
    if not evs:
        return []
    last = evs[-1]
    return [f"committee ({len(evs)} reseat(s), newest epoch "
            f"{last.get('epoch')}): "
            f"{', '.join(last.get('seats') or []) or '?'}"]


def render_once(timeline, art_dir: str = "") -> str:
    scrapes = [r for r in timeline if r.get("type") == "scrape"]
    if not scrapes:
        return "no scrapes in timeline (telemetry disabled or empty run)"
    last = scrapes[-1]
    cov = last.get("coverage", {})
    lines = [f"scrape tag={last.get('tag')}  "
             f"coverage {cov.get('answered')}/{cov.get('expected')}"
             + (f"  epoch={last['epoch']}" if "epoch" in last else "")
             + (f"  missing: {', '.join(cov['missing'])}"
                if cov.get("missing") else "")]
    for role in sorted(last.get("roles", {})):
        lines.append(_role_row(role, last["roles"][role]))
    lines.extend(_committee_panel(art_dir))
    lines.extend(_slo_panel(art_dir))
    return "\n".join(lines)


def _scrape_digest(rec) -> str:
    """One compressed line per scrape for the timeline view."""
    bits = []
    roles = rec.get("roles", {})
    w = roles.get("writer")
    if w:
        bits.append(f"round={int(_gauge_value(w, 'round', 0))} "
                    f"backlog={int(_gauge_value(w, 'uncertified_backlog', 0))}")
        n_c, m_c = _merged_hist(w, "certify_latency_seconds")
        if n_c:
            bits.append(f"certify~{m_c * 1e3:.0f}ms x{n_c}")
        age = _gauge_value(w, "snapshot_age_rounds")
        if age is not None and age >= 0:
            bits.append(f"snap-age={int(age)} "
                        f"base={int(_gauge_value(w, 'log_base', 0))}")
        aggs = _sum_counter(w, "async_aggregations_total")
        if aggs:
            bits.append(
                f"async-buf={int(_gauge_value(w, 'async_buffer_depth', 0))} "
                f"aggs={aggs:.0f}")
    for role in sorted(roles):
        # WARN/CRIT health rounds surface on the timeline (quiet when
        # OK) — from ANY monitored role: the writer or a cell
        # aggregator (member-level verdicts never reach the root)
        if role == "writer" or role.startswith("cell"):
            hv = _gauge_value(roles[role], "health_verdict")
            if hv:
                bits.append(
                    f"{role}: health="
                    f"{('OK', 'WARN', 'CRIT')[min(int(hv), 2)]} "
                    f"flagged="
                    f"{int(_gauge_value(roles[role], 'health_flagged_senders', 0))}")
        if role.startswith("cell"):
            adm = _gauge_value(roles[role], "cell_admitted", 0)
            n_a, m_a = _merged_hist(roles[role],
                                    "cell_root_ack_seconds")
            if adm or n_a:
                bits.append(f"{role}: adm={int(adm)} "
                            f"certify~{m_a * 1e3:.0f}ms")
        if role.startswith("standby"):
            lag = _gauge_value(roles[role], "standby_ack_lag_ops", 0)
            promos = _sum_counter(roles[role],
                                  "standby_promotions_total")
            if lag or promos:
                bits.append(f"{role}: lag={int(lag)} "
                            f"promos={promos:.0f}")
            n_ss, _ = _merged_hist(roles[role], "state_sync_seconds")
            if n_ss:
                bits.append(f"{role}: state-syncs={n_ss}")
        if role.startswith("validator"):
            rep = _sum_counter(roles[role], "repair_events_total")
            if rep:
                bits.append(f"{role}: repairs={rep:.0f}")
            ref = _sum_counter(roles[role], "rederive_refusals_total")
            skip = _sum_counter(roles[role], "rederive_skipped_total")
            if ref or skip:
                # a refused commit / a counted degrade is exactly the
                # kind of event the timeline should interleave
                bits.append(f"{role}: rederive refuse={ref:.0f} "
                            f"skip={skip:.0f}")
    cov = rec.get("coverage", {})
    if cov.get("missing"):
        bits.append(f"dark: {','.join(cov['missing'])}")
    return "  ".join(bits) or "(quiet)"


def render_timeline(timeline, spans_dir: str = "") -> str:
    recs = [r for r in timeline
            if r.get("type") in ("scrape", "fault", "note")]
    if spans_dir:
        # SLO burn-rate pages (obs.slo) interleave on the same stream:
        # the alert is read next to the fault/scrape that caused it
        from bflc_demo_tpu.obs.slo import load_alerts
        recs.extend(load_alerts(spans_dir))
        # committee reseats (async re-election) interleave too: the
        # seating change is read next to the drain that carried it
        recs.extend({"type": "reseat", **e}
                    for e in _reseat_events(spans_dir))
        # certified genome updates (closed-loop compression)
        # interleave as well: the knob transition is read next to the
        # commit and telemetry that decided it
        recs.extend({"type": "genome", **e}
                    for e in _genome_events(spans_dir))
    if not recs:
        return "empty timeline"
    t0 = min(r.get("t", 0.0) for r in recs)
    lines = []
    for r in sorted(recs, key=lambda r: r.get("t", 0.0)):
        dt = r.get("t", 0.0) - t0
        if r["type"] == "fault":
            what = (f"{r.get('kind', '?')} {r.get('target', '')}"
                    f"{'' if r.get('executed', True) else ' (skipped)'}")
            lines.append(f"+{dt:7.1f}s  FAULT   {what.strip()}")
        elif r["type"] == "reseat":
            changed = r.get("changed") or []
            lines.append(
                f"+{dt:7.1f}s  RESEAT  epoch {r.get('epoch')}: "
                f"{','.join(r.get('seats') or []) or '?'}"
                + (f" (in: {','.join(changed)})" if changed else ""))
        elif r["type"] == "genome":
            bits = []
            if r.get("old_density") != r.get("new_density"):
                bits.append(f"density {r.get('old_density'):g}->"
                            f"{r.get('new_density'):g}")
            if r.get("old_staleness") != r.get("new_staleness"):
                bits.append(f"staleness {r.get('old_staleness')}->"
                            f"{r.get('new_staleness')}")
            lines.append(
                f"+{dt:7.1f}s  GENOME  commit "
                f"{r.get('commit_epoch')}: "
                + (" ".join(bits) or "knobs held")
                + f" (disagree={r.get('disagreement'):.3g} "
                  f"drift={r.get('drift'):.3g} "
                  f"norm={r.get('update_norm'):.3g})")
        elif r["type"] == "slo_alert":
            lines.append(
                f"+{dt:7.1f}s  ALERT   {r.get('slo')} round "
                f"{r.get('epoch')}: {r.get('signal')}={r.get('value')} "
                f"vs {r.get('op')} {r.get('bound')} "
                f"(burn {r.get('burn_fast')}/{r.get('burn_slow')})")
        elif r["type"] == "note":
            extras = {k: v for k, v in r.items()
                      if k not in ("type", "t", "name")}
            lines.append(f"+{dt:7.1f}s  note    {r.get('name')} "
                         + " ".join(f"{k}={v}" for k, v in
                                    sorted(extras.items())))
        else:
            lines.append(f"+{dt:7.1f}s  scrape  "
                         f"[{r.get('tag')}] {_scrape_digest(r)}")
    lines.extend(_critical_path_block(timeline, spans_dir))
    return "\n".join(lines)


def _critical_path_block(timeline, spans_dir: str):
    """Per-round critical paths from the run's causal traces
    (obs.trace), appended under the fault/metric timeline so an
    injected delay is read next to the segment it stretched.  Empty
    when the run was untraced (no *.spans.jsonl in the dir)."""
    if not spans_dir:
        return []
    try:
        names = os.listdir(spans_dir)
    except OSError:
        return []
    if not any(n.endswith(".spans.jsonl") for n in names):
        return []
    from bflc_demo_tpu.obs import trace as obs_trace
    spans = obs_trace.gather_spans(spans_dir)
    faults = [r for r in timeline if r.get("type") == "fault"]
    reports = obs_trace.round_reports(spans, faults=faults)
    if not reports:
        return []
    lines = ["", "critical paths (causal traces, tools/trace_report.py "
                 "for the full view):"]
    for rep in reports:
        lines.append(obs_trace.format_round_report(rep))
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="metrics.jsonl (or its directory)")
    ap.add_argument("--once", action="store_true",
                    help="render the latest scrape and exit")
    ap.add_argument("--timeline", action="store_true",
                    help="render the fault/metric post-mortem timeline")
    ap.add_argument("--refresh", type=float, default=2.0,
                    help="live-mode refresh period (seconds)")
    args = ap.parse_args(argv)

    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    if not os.path.exists(path):
        print(f"no such artifact: {path}", file=sys.stderr)
        return 2

    if args.timeline:
        print(render_timeline(load_timeline(path),
                              spans_dir=os.path.dirname(
                                  os.path.abspath(path))))
        return 0
    art_dir = os.path.dirname(os.path.abspath(path))
    if args.once:
        print(render_once(load_timeline(path), art_dir=art_dir))
        return 0
    try:
        while True:
            out = render_once(load_timeline(path), art_dir=art_dir)
            sys.stdout.write("\x1b[2J\x1b[H" if sys.stdout.isatty()
                             else "")
            print(time.strftime("%H:%M:%S"), "—", path)
            print(out, flush=True)
            time.sleep(args.refresh)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
