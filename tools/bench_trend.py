#!/usr/bin/env python
"""bench_trend: the BENCH_r*.json trajectory as a per-metric trend
table, with regression flags.

Every PR snapshots `bench.py`'s JSON line into a `BENCH_r<round>.json`
artifact (wrapped by the capture harness as {"n": round, "parsed":
{metric, value, extra...}}), but the trajectory was never collected —
nothing would catch a perf regression between PRs.  This tool parses
the whole series, extracts the headline axes plus the `extra.*`
numbers each PR added, and flags any round whose value regressed more
than --threshold (default 10%) against the BEST prior round on that
metric.

Caveat the artifacts themselves document: round TIMES on the
cpu-fallback host have CV > 1 (BENCH notes / VERDICT r5), so time-axis
flags on this host are a prompt to look, not a verdict — accuracy and
byte-count axes are the stable ones.

Usage:
    python tools/bench_trend.py [repo_dir] [--json] [--threshold 0.1]
        [--strict]

--strict exits 1 when any regression is flagged (CI hook); default
exit is 0 with flags printed.
"""

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

# (label, dotted path under the parsed record, direction, mode) —
# direction "higher" = bigger is better, "lower" = smaller is better;
# mode "rel" flags a RELATIVE change vs the best prior round, "abs" an
# ABSOLUTE one (for signed near-zero metrics like the overhead
# fractions, where dividing noise around 0 by 0 manufactures huge
# spurious percentages).  Missing paths are skipped per round (axes
# appear as PRs add them).
METRICS: List[Tuple[str, str, str, str]] = [
    ("best_test_acc", "extra.best_test_acc", "higher", "rel"),
    ("round_time_s", "value", "lower", "rel"),
    ("warm_median_round_time_s",
     "extra.batched_warm_median_round_time_s", "lower", "rel"),
    ("samples_per_sec_per_chip",
     "extra.train_samples_per_sec_per_chip", "higher", "rel"),
    ("federation_round_wall_s",
     "extra.federation.fast.round_wall_time_s", "lower", "rel"),
    ("ops_certified_per_sec",
     "extra.federation.fast.ops_certified_per_sec", "higher", "rel"),
    ("egress_bytes_per_round",
     "extra.data_plane.egress_bytes_per_round", "lower", "rel"),
    ("trace_overhead_frac", "extra.trace_overhead.overhead_frac",
     "lower", "abs"),
    ("health_overhead_frac", "extra.health_overhead.overhead_frac",
     "lower", "abs"),
    ("async_throughput_speedup",
     "extra.async_agg.round_throughput_speedup", "higher", "rel"),
    # sparse upload deltas (eval.benchmarks.sparse_config1, bench.py
    # extra.sparse): the density-sweep headline — writer egress/round
    # at the sparsest leg and its multiple vs the dense-f32 leg — so a
    # >10% regression in either direction of the sweep flags
    ("sparse_egress_bytes_per_round",
     "extra.sparse.sparsest_egress_bytes_per_round", "lower", "rel"),
    ("sparse_egress_vs_legacy_x",
     "extra.sparse.egress_vs_legacy_dense_f32_x", "higher", "rel"),
    # async endurance campaign (eval.benchmarks.endurance_async_config1,
    # bench.py extra.endurance_async): the reseat/churn regime.  WAL and
    # held-op ceilings must stay bounded as the campaign lengthens;
    # wedge/false-page counts are zero-tolerance so any absolute uptick
    # flags; reseat count is a coverage axis — fewer reseats per run
    # means the re-election plane quietly stopped exercising.
    ("endurance_reseats",
     "extra.endurance_async.reseats", "higher", "rel"),
    ("endurance_max_wal_bytes",
     "extra.endurance_async.max_wal_bytes", "lower", "rel"),
    ("endurance_2nd_half_wal_bytes",
     "extra.endurance_async.second_half_max_wal_bytes", "lower", "rel"),
    ("endurance_max_held_ops",
     "extra.endurance_async.max_held_ops", "lower", "rel"),
    ("endurance_departed_wedged",
     "extra.endurance_async.departed_wedged", "lower", "abs"),
    ("endurance_slo_false_pages",
     "extra.endurance_async.slo_false_pages", "lower", "abs"),
    # blocked reduction (eval.benchmarks.blocked_agg_config1, bench.py
    # extra.blocked_agg, REDUCTION SPEC v2): the agg speedup of the
    # best blocked cell vs the v1 mesh leg at matched (largest) N, and
    # the sharded-model leg's wall — the geometry whose (N, P) stack
    # exceeds the v1 single-buffer staging path.  Time axes, so on the
    # cpu-fallback host a flag is a prompt to look, not a verdict.
    ("blocked_agg_speedup_x",
     "extra.blocked_agg.agg_speedup_vs_v1_x", "higher", "rel"),
    ("blocked_sharded_wall_s",
     "extra.blocked_agg.sharded_model.blocked_wall_s", "lower", "rel"),
    # device-plane observability (obs.device, bench.py extra.device /
    # extra.device_overhead): the armed-vs-BFLC_DEVICE_OBS=0 round-wall
    # ratio is a near-zero fraction ("abs" — the 1% bar), and both
    # recompile axes are zero-tolerance: post-warmup fleet fresh
    # compiles and the repeated-scenario steady-state gate must stay
    # at zero, so ANY absolute uptick flags.
    ("device_overhead_frac",
     "extra.device_overhead.overhead_frac", "lower", "abs"),
    ("device_steady_recompiles",
     "extra.device_overhead.steady_state_recompiles", "lower", "abs"),
    ("device_gate_fresh_compiles",
     "extra.device.steady_state_gate.fresh_after_warmup", "lower",
     "abs"),
    # closed-loop compression (eval.benchmarks.closed_loop_config1,
    # bench.py extra.closed_loop, ISSUE 20): EF egress reduction at
    # matched accuracy (only populated when the EF leg stayed within
    # 0.02 of dense — a drop to '-' IS the flag that accuracy parity
    # broke), the EF-vs-dense accuracy gap (signed near-zero: "abs"),
    # the catch-up vs the stateless-sparse trail, and rounds-to-0.85
    # under EF (time-to-quality; fewer is better).
    ("closed_loop_egress_matched_x",
     "extra.closed_loop.egress_reduction_at_matched_acc_x", "higher",
     "rel"),
    ("closed_loop_acc_gap_ef",
     "extra.closed_loop.acc_gap_ef", "lower", "abs"),
    ("closed_loop_acc_catch_up",
     "extra.closed_loop.acc_catch_up", "higher", "abs"),
    ("closed_loop_rounds_to_085_ef",
     "extra.closed_loop.rounds_to_085_ef", "lower", "rel"),
]

# Derived axes: computed by a function over the parsed record instead
# of a dotted path — for terminal keys a dotted path cannot address
# (leg names like "d0.01_f32" contain dots) or values derived from
# several fields.  Same (label, extractor, direction, mode) semantics.
def _sparse_acc_catch_up(rec: Dict[str, Any]) -> Optional[float]:
    """The accuracy-catch-up axis over the EXISTING extra.sparse
    artifacts: how far the sparsest stateless top-k leg trails the
    dense-f32 leg (extra.sparse.acc_gap_vs_dense_f32 is keyed by leg
    name).  This is the trail error feedback exists to close — once
    extra.closed_loop lands, closed_loop_acc_catch_up shows how much
    of THIS number EF recovered."""
    gaps = rec.get("extra", {}).get("sparse", {}) \
        .get("acc_gap_vs_dense_f32")
    if not isinstance(gaps, dict):
        return None
    sparse = {k: v for k, v in gaps.items()
              if k.startswith("d") and not k.startswith("d1_")
              and isinstance(v, (int, float))}
    if not sparse:
        return None
    # the sparsest f32 leg (smallest density) — the headline trail
    def _dens(k: str) -> float:
        try:
            return float(k[1:].rsplit("_", 1)[0])
        except ValueError:
            return 1.0
    return float(sparse[min(sparse, key=_dens)])


DERIVED: List[Tuple[str, Any, str, str]] = [
    ("sparse_acc_gap_sparsest", _sparse_acc_catch_up, "lower", "abs"),
]


def _dig(rec: Dict[str, Any], path: str) -> Optional[float]:
    cur: Any = rec
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def load_series(repo_dir: str) -> List[Tuple[int, Dict[str, Any]]]:
    """[(round_n, parsed record)] sorted by round, from BENCH_r*.json.
    The capture wrapper ({"n", "parsed"}) and a bare bench.py line are
    both accepted."""
    out = []
    for path in glob.glob(os.path.join(repo_dir, "BENCH_r*.json")):
        try:
            with open(path) as fh:
                raw = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = raw.get("parsed") if isinstance(raw.get("parsed"), dict) \
            else raw
        if not isinstance(rec, dict) or rec.get("metric") is None:
            continue
        n = raw.get("n")
        if n is None:
            m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
            n = int(m.group(1)) if m else 0
        out.append((int(n), rec))
    out.sort(key=lambda t: t[0])
    return out


def trend(series: List[Tuple[int, Dict[str, Any]]],
          threshold: float = 0.10) -> Dict[str, Any]:
    """{metrics: {label: [(round, value)]}, regressions: [...]}.
    A regression at round r: worse than the best PRIOR round by more
    than `threshold` (relative)."""
    metrics: Dict[str, List[Tuple[int, float]]] = {}
    regressions: List[Dict[str, Any]] = []
    axes = [(lb, (lambda rec, p=path: _dig(rec, p)), d, m)
            for lb, path, d, m in METRICS]
    axes += [(lb, fn, d, m) for lb, fn, d, m in DERIVED]
    for label, extract, direction, mode in axes:
        pts = [(n, extract(rec)) for n, rec in series]
        pts = [(n, v) for n, v in pts if v is not None]
        if not pts:
            continue
        metrics[label] = pts
        best: Optional[float] = None
        for n, v in pts:
            if best is not None and (mode == "abs" or best != 0):
                delta = (v - best if direction == "lower"
                         else best - v)
                worse = delta if mode == "abs" else delta / abs(best)
                if worse > threshold:
                    regressions.append({
                        "metric": label, "round": n, "value": v,
                        "best_prior": best, "mode": mode,
                        "worse_frac": round(worse, 4),
                        "direction": direction})
            best = (v if best is None
                    else (min(best, v) if direction == "lower"
                          else max(best, v)))
    return {"rounds": [n for n, _ in series], "threshold": threshold,
            "metrics": metrics, "regressions": regressions}


def render_table(report: Dict[str, Any]) -> str:
    rounds = report["rounds"]
    lines = ["bench trajectory (rounds: "
             + ", ".join(str(n) for n in rounds) + ")", ""]
    head = f"{'metric':<28}" + "".join(f"{('r' + str(n)):>12}"
                                       for n in rounds)
    lines += [head, "-" * len(head)]
    flagged = {(r["metric"], r["round"])
               for r in report["regressions"]}
    for label, pts in report["metrics"].items():
        by_round = dict(pts)
        cells = []
        for n in rounds:
            v = by_round.get(n)
            if v is None:
                cells.append(f"{'-':>12}")
            else:
                mark = "!" if (label, n) in flagged else ""
                cells.append(f"{v:>11.4g}{mark or ' '}")
        lines.append(f"{label:<28}" + "".join(cells))
    lines.append("")
    if report["regressions"]:
        lines.append(f"{len(report['regressions'])} regression(s) "
                     f"> {report['threshold']:.0%} vs best prior "
                     f"round ('!' above):")
        for r in report["regressions"]:
            lines.append(
                f"  {r['metric']} @ r{r['round']}: {r['value']:.4g} "
                f"vs best {r['best_prior']:.4g} "
                f"({r['worse_frac']:+.1%} worse)")
    else:
        lines.append("no regressions vs best prior round")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("repo_dir", nargs="?",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding BENCH_r*.json "
                         "(default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression flag bar (default 0.10)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any regression is flagged")
    args = ap.parse_args(argv)

    series = load_series(args.repo_dir)
    if not series:
        print(f"no BENCH_r*.json under {args.repo_dir}",
              file=sys.stderr)
        return 2
    report = trend(series, threshold=args.threshold)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_table(report))
    return 1 if (args.strict and report["regressions"]) else 0


if __name__ == "__main__":
    sys.exit(main())
