#!/usr/bin/env python
"""health_report: post-mortem renderer for the model-quality health
plane (bflc_demo_tpu.obs.health).

Input is a telemetry dir (or a single file) holding one or more
``<role>.health.jsonl`` record streams — one JSON object per committed
round, written by the writer / cell aggregators of a run with the
health plane armed.  Output:

- a per-round **verdict table** (epoch, tier, verdict, update norm,
  model drift, committee score median/IQR/disagreement, staleness);
- a **flagged-sender ranking** (crit/warn counts, worst |z|, rules
  tripped) — the "who attacked us" view;
- the **contribution ledger** (per-sender admitted/selected counts and
  cumulative merge-weight share).

Usage:
    python tools/health_report.py <telemetry_dir | health.jsonl> \
        [--json] [--out health_report_<tag>.json]

Markdown to stdout by default; --json prints the machine-readable
summary instead; --out additionally writes it to a file.  Verdicts are
observability only — this tool renders what the fleet saw, it gates
nothing (PARITY.md: the health plane changes no trust).
"""

import argparse
import json
import os
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the loader moved into the package (obs.health) so the chaos_soak
# --fail-on-crit gate and the forensics joiner share it; re-exported
# here because this tool is its historical home
from bflc_demo_tpu.obs.health import (  # noqa: E402,F401
    load_health_records, summarize_records)


def render_markdown(summary: Dict, records: List[dict]) -> str:
    lines = ["# Model-quality health report", ""]
    v = summary["verdicts"]
    lines.append(f"{summary['rounds']} rounds — "
                 f"ok {v.get('ok', 0)} / warn {v.get('warn', 0)} / "
                 f"crit {v.get('crit', 0)}")
    lines += ["", "## Per-round verdicts", "",
              "| epoch | role | mode | verdict | flagged | upd norm | "
              "drift | score med | IQR | disagree | staleness |",
              "|---|---|---|---|---|---|---|---|---|---|---|"]
    for rec in records:
        st = rec.get("staleness")
        st_s = (f"{st['min']}-{st['max']} (~{st['mean']})"
                if st else "-")
        lines.append(
            f"| {rec.get('epoch')} | {rec.get('role', '?')} "
            f"| {rec.get('mode')} "
            f"| {rec.get('verdict', 'ok').upper()} "
            f"| {rec.get('flagged', 0)}/{rec.get('n', 0)} "
            f"| {rec.get('update_norm', 0):.4g} "
            f"| {rec.get('model_drift', 0):.4g} "
            f"| {rec.get('score_median', 0):.3f} "
            f"| {rec.get('score_iqr', 0):.3f} "
            f"| {rec.get('score_disagreement', 0):.3f} "
            f"| {st_s} |")
    lines += ["", "## Flagged senders", ""]
    if not summary["flagged_senders"]:
        lines.append("(none — every delta inside the fleet baseline)")
    else:
        lines += ["| sender | crit | warn | worst \\|z\\| | rules |",
                  "|---|---|---|---|---|"]
        for f in summary["flagged_senders"]:
            lines.append(f"| {f['sender']} | {f['crit']} | {f['warn']} "
                         f"| {f['max_abs_z']:.1f} "
                         f"| {', '.join(f['reasons'])} |")
    contrib = summary.get("contribution") or {}
    if contrib:
        lines += ["", "## Contribution ledger", "",
                  "| sender | admitted | selected | weight share |",
                  "|---|---|---|---|"]
        ranked = sorted(contrib.items(),
                        key=lambda kv: -kv[1].get("weight_share", 0.0))
        for sender, c in ranked:
            lines.append(
                f"| {sender} | {c.get('admitted', 0)} "
                f"| {c.get('selected', 0)} "
                f"| {c.get('weight_share', 0.0):.3f} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path",
                    help="telemetry dir (globs *.health.jsonl) or one "
                         "health.jsonl file")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON summary instead of markdown")
    ap.add_argument("--out", default="",
                    help="also write the JSON summary to this file")
    args = ap.parse_args(argv)

    records = load_health_records(args.path)
    if not records:
        print(f"no health records under {args.path} "
              f"(health plane unarmed, or BFLC_HEALTH_LEGACY=1 run)",
              file=sys.stderr)
        return 2
    summary = summarize_records(records)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(summary, fh, indent=2)
            fh.write("\n")
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(render_markdown(summary, records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
