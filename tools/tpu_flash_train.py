"""Long-context TRAINING step on the real chip: flash vs einsum backward.

VERDICT round-2 weak #4 asked for a backward that doesn't rematerialise the
(S, S) logits, proven by "a TPU-measured training step at seq 8192 that the
einsum backward cannot fit/match".  This tool measures exactly that: one
SGD step (value_and_grad through a 1-block transformer) at increasing
sequence lengths with attention_impl=pallas (blockwise dq/dk/dv from saved
LSE) vs einsum (XLA autodiff, full logits in the backward), bf16.

Each (impl, seq) cell runs in a child process under a watchdog so an OOM or
a wedged tunnel kills the cell, not the sweep.  Appends a table to
TPU_RESULTS.md and prints one JSON line per cell.

Usage: python tools/tpu_flash_train.py [--seqs 2048,4096,8192]
       [--timeout 900] [--out TPU_RESULTS.md]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

CHILD_CODE = """
import time, json
import numpy as np
import jax, jax.numpy as jnp
from bflc_demo_tpu.utils.compile_cache import enable_persistent_cache
from bflc_demo_tpu.models.transformer import make_transformer_classifier
enable_persistent_cache()
impl, seq = {impl!r}, {seq}
model = make_transformer_classifier(
    vocab_size=512, seq_len=seq, num_classes=2, dim=256, depth=1, heads=4,
    dtype=jnp.bfloat16, attention_impl=impl)
cfg = model.config
rng = np.random.default_rng(0)
b = 2
toks = jnp.asarray(rng.integers(1, 512, (b, seq)), jnp.int32)
labels = jnp.asarray(np.eye(2, dtype=np.float32)[rng.integers(0, 2, b)])
params = model.init_params(0)
params["head_w"] = jnp.asarray(
    rng.standard_normal((cfg.dim, 2)), jnp.float32) * 0.02

def loss_fn(p):
    logits = model.apply(p, toks)
    return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), -1))

step = jax.jit(jax.value_and_grad(loss_fn))
loss, grads = step(params)          # compile
jax.block_until_ready(grads)
reps = 5
t0 = time.perf_counter()
for _ in range(reps):
    loss, grads = step(params)
jax.block_until_ready(grads)
dt = (time.perf_counter() - t0) / reps
finite = all(bool(jnp.isfinite(g).all())
             for g in jax.tree_util.tree_leaves(grads))
print("RESULT " + json.dumps({{
    "impl": impl, "seq": seq, "batch": b,
    "platform": jax.devices()[0].platform,
    "train_step_ms": round(dt * 1e3, 2),
    "loss": round(float(loss), 5), "grads_finite": finite,
}}))
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="2048,4096,8192")
    ap.add_argument("--timeout", type=int, default=900)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    rows = []
    for seq in (int(s) for s in args.seqs.split(",")):
        for impl in ("pallas", "einsum"):
            code = CHILD_CODE.format(impl=impl, seq=seq)
            try:
                t0 = time.time()
                proc = subprocess.run([sys.executable, "-c", code],
                                      capture_output=True, text=True,
                                      timeout=args.timeout,
                                      env=dict(os.environ))
                line = next((ln for ln in proc.stdout.splitlines()
                             if ln.startswith("RESULT ")), None)
                if proc.returncode == 0 and line:
                    rows.append(json.loads(line[len("RESULT "):]))
                else:
                    err = proc.stderr.strip()[-300:]
                    rows.append({"impl": impl, "seq": seq,
                                 "error": f"rc={proc.returncode}: {err}"})
            except subprocess.TimeoutExpired:
                rows.append({"impl": impl, "seq": seq,
                             "error": f"timeout {args.timeout}s "
                                      f"(after {time.time() - t0:.0f}s)"})
            print(json.dumps(rows[-1]), flush=True)

    if args.out:
        with open(args.out, "a") as f:
            f.write(f"\n## tools/tpu_flash_train.py run "
                    f"({time.strftime('%Y-%m-%d %H:%M')}) — bf16 training "
                    f"step, 1 block, dim 256, 4 heads, batch 2\n\n")
            f.write("| seq | impl | train step ms | note |\n"
                    "|---|---|---|---|\n")
            for r in rows:
                if "error" in r:
                    f.write(f"| {r['seq']} | {r['impl']} | — | "
                            f"{r['error'][:90]} |\n")
                else:
                    f.write(f"| {r['seq']} | {r['impl']} | "
                            f"{r['train_step_ms']} | "
                            f"platform={r['platform']} "
                            f"finite={r['grads_finite']} |\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
