#!/usr/bin/env python
"""incident_bundle: export a self-contained forensic tar around an alert.

The page hand-off artifact: given a telemetry dir and an SLO alert (or
an explicit round), slice EVERY artifact stream to a ±K-round window
around it — metrics.jsonl scrapes/faults/notes, per-role health
records, causal spans, flight-recorder events, the alerts file — and
pack one ``incident_<slo>_r<epoch>.tar`` whose ``narrative.md``
reconstructs the cross-pillar story (what paged, the round's critical
path, the health verdict and flagged senders, the faults in window), so
the person paged at 3am gets evidence, not a directory of five file
formats.

    python tools/incident_bundle.py <telemetry_dir>            # newest
        # alert, ±3 rounds
    python tools/incident_bundle.py <dir> --slo health_budget  # newest
        # alert of that objective
    python tools/incident_bundle.py <dir> --round 41 --k 5     # window
        # around a round with no alert (manual forensics)

Slices stay in their native formats — every bundled stream re-parses
with the same loaders (obs.timeline.load_round_timeline works on an
extracted bundle).
"""

import argparse
import io
import json
import os
import sys
import tarfile
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bflc_demo_tpu.obs import device as obs_device      # noqa: E402
from bflc_demo_tpu.obs import slo as obs_slo            # noqa: E402
from bflc_demo_tpu.obs import trace as obs_trace        # noqa: E402
from bflc_demo_tpu.obs.collector import load_timeline   # noqa: E402
from bflc_demo_tpu.obs.timeline import (                # noqa: E402
    load_round_timeline, round_of_scrape)


def pick_alert(alerts: List[dict], slo: str = "",
               index: Optional[int] = None) -> Optional[dict]:
    """The alert to bundle: --alert index wins, else the NEWEST alert
    (optionally of a named objective) — pages triage newest-first."""
    if slo:
        alerts = [a for a in alerts if a.get("slo") == slo]
    if not alerts:
        return None
    if index is not None:
        return alerts[index] if 0 <= index < len(alerts) else None
    return alerts[-1]


def _slice_jsonl_records(records: List[dict], keep) -> bytes:
    buf = io.StringIO()
    for rec in records:
        if keep(rec):
            buf.write(json.dumps(rec) + "\n")
    return buf.getvalue().encode()


def _add_bytes(tar: tarfile.TarFile, name: str, data: bytes) -> None:
    info = tarfile.TarInfo(name)
    info.size = len(data)
    info.mtime = int(time.time())
    tar.addfile(info, io.BytesIO(data))


def build_bundle(telemetry_dir: str, out_path: str, *,
                 slo: str = "", alert_index: Optional[int] = None,
                 around_round: Optional[int] = None,
                 k: int = 3) -> dict:
    """Write the tar; returns the manifest (raises ValueError when
    nothing anchors a window)."""
    tl = load_round_timeline(telemetry_dir)
    alerts = obs_slo.load_alerts(telemetry_dir) or list(tl.alerts)
    alert = None
    if around_round is None:
        alert = pick_alert(alerts, slo=slo, index=alert_index)
        if alert is None:
            raise ValueError(
                "no matching alert in alerts.jsonl — pass --round to "
                "bundle a window without one")
        center = int(alert.get("epoch") or 0)
    else:
        center = int(around_round)
    lo_r, hi_r = center - k, center + k
    bounds = [tl.round_bounds(r) for r in range(lo_r, hi_r + 1)]
    t_los = [b[0] for b in bounds if b[0] is not None]
    t_his = [b[1] for b in bounds if b[1] is not None]
    t_lo = min(t_los) if t_los else None
    t_hi = max(t_his) if t_his else None

    def _in_wall(t) -> bool:
        if not isinstance(t, (int, float)):
            return False
        return ((t_lo is None or t >= t_lo - 1.0)
                and (t_hi is None or t <= t_hi + 1.0))

    def _keep_metrics(rec) -> bool:
        if rec.get("type") == "scrape":
            r = round_of_scrape(rec)
            if r is not None:
                return lo_r <= r <= hi_r
        ep = rec.get("epoch")
        if isinstance(ep, int) and rec.get("type") == "note":
            return lo_r <= ep <= hi_r
        return _in_wall(rec.get("t"))

    files: List[str] = []
    with tarfile.open(out_path, "w") as tar:
        mpath = os.path.join(telemetry_dir, "metrics.jsonl")
        if os.path.exists(mpath):
            data = _slice_jsonl_records(load_timeline(mpath),
                                        _keep_metrics)
            _add_bytes(tar, "metrics.slice.jsonl", data)
            files.append("metrics.slice.jsonl")
        try:
            names = sorted(os.listdir(telemetry_dir))
        except OSError:
            names = []
        for name in names:
            path = os.path.join(telemetry_dir, name)
            if name.endswith(".health.jsonl"):
                data = _slice_jsonl_records(
                    load_timeline(path),
                    lambda rec: isinstance(rec.get("epoch"), int)
                    and lo_r <= rec["epoch"] <= hi_r)
            elif name.endswith(".spans.jsonl"):
                # wall-anchored re-serialization (load_spans applied
                # the header offset; headerless slices re-load with
                # offset 0, i.e. unchanged)
                spans = obs_trace.load_spans(path)
                data = _slice_jsonl_records(
                    spans, lambda s: _in_wall(s.get("t0"))
                    or _in_wall(s.get("t1")))
            elif name.endswith(".flight.jsonl"):
                # keep the header line so load_flight still parses
                try:
                    with open(path) as fh:
                        lines = fh.read().splitlines()
                except OSError:
                    continue
                kept = lines[:1] + [
                    ln for ln in lines[1:]
                    if _keep_flight_line(ln, _in_wall)]
                data = ("\n".join(kept) + "\n").encode() \
                    if kept else b""
            elif name.endswith(".device.jsonl"):
                # device-plane records (obs.device): storm verdicts
                # slice by epoch, compile/memory/xprof events by wall
                data = _slice_jsonl_records(
                    obs_device.load_device_records(path),
                    lambda rec: (lo_r <= rec["epoch"] <= hi_r
                                 if isinstance(rec.get("epoch"), int)
                                 else _in_wall(rec.get("t"))))
            elif name == "alerts.jsonl":
                try:
                    with open(path, "rb") as fh:
                        data = fh.read()
                except OSError:
                    continue
            else:
                continue
            if data:
                _add_bytes(tar, f"slices/{name}", data)
                files.append(f"slices/{name}")
        narrative = _narrative(tl, alert, center, lo_r, hi_r)
        _add_bytes(tar, "narrative.md", narrative.encode())
        files.append("narrative.md")
        # profiler capture windows (obs.device.XprofWindow): register
        # the artifact dir by reference — capture trees are large and
        # tool-specific, so the bundle carries the pointer + listing,
        # never the bytes
        xprof = _xprof_registration(telemetry_dir)
        manifest = {
            "type": "incident_bundle", "t": time.time(),
            "telemetry_dir": os.path.abspath(telemetry_dir),
            "alert": alert, "round": center,
            "window_rounds": [lo_r, hi_r],
            "window_wall": [t_lo, t_hi],
            "files": files,
            "xprof": xprof,
        }
        _add_bytes(tar, "manifest.json",
                   (json.dumps(manifest, indent=2) + "\n").encode())
    return manifest


def _xprof_registration(telemetry_dir: str) -> Optional[dict]:
    """The run's profiler-capture dirs: the default <dir>/xprof tree
    plus any dir a device_xprof record points at.  {dir: [relative
    files...]} or None when the run captured nothing."""
    dirs = []
    default = os.path.join(telemetry_dir, "xprof")
    if os.path.isdir(default):
        dirs.append(default)
    for rec in obs_device.load_device_records(telemetry_dir):
        d = rec.get("dir")
        if rec.get("type") == "device_xprof" and d \
                and os.path.isdir(d) and d not in dirs:
            dirs.append(d)
    if not dirs:
        return None
    out = {}
    for d in dirs:
        listing = []
        for root, _, names in os.walk(d):
            for name in sorted(names):
                listing.append(os.path.relpath(
                    os.path.join(root, name), d))
        out[os.path.abspath(d)] = sorted(listing)
    return out


def _keep_flight_line(line: str, in_wall) -> bool:
    line = line.strip()
    if not line:
        return False
    try:
        rec = json.loads(line)
    except ValueError:
        return False
    return in_wall(rec.get("t"))


def _narrative(tl, alert: Optional[dict], center: int,
               lo_r: int, hi_r: int) -> str:
    """The reconstructed cross-pillar story (markdown) — obs_query's
    round renderer over the window, led by the page itself."""
    import obs_query
    lines = ["# Incident bundle narrative", ""]
    if alert is not None:
        lines.append(
            f"**Paged:** SLO `{alert['slo']}` at round "
            f"{alert.get('epoch')} — {alert['signal']}="
            f"{alert.get('value')} vs {alert['op']} {alert['bound']} "
            f"(burn fast/slow {alert.get('burn_fast')}/"
            f"{alert.get('burn_slow')}, budget {alert.get('budget')})")
    else:
        lines.append(f"**Manual forensics window** around round "
                     f"{center} (no alert)")
    lines.append(f"Window: rounds {lo_r}..{hi_r}")
    present = [r for r in tl.rounds() if lo_r <= r <= hi_r]
    lines += ["", obs_query.render_summary(
        tl, [tl.round_record(r) for r in present])]
    for r in present:
        lines += ["", "---", "", obs_query.render_round(
            tl.round_record(r))]
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="telemetry dir")
    ap.add_argument("--slo", default="",
                    help="bundle the newest alert of this objective")
    ap.add_argument("--alert", type=int, default=None,
                    help="alerts.jsonl index to bundle (default: "
                         "newest)")
    ap.add_argument("--round", type=int, default=None,
                    help="bundle around this round instead of an alert")
    ap.add_argument("--k", type=int, default=3,
                    help="window half-width in rounds (default 3)")
    ap.add_argument("--out", default="",
                    help="tar path (default incident_<slo>_r<N>.tar)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.path):
        print(f"no such telemetry dir: {args.path}", file=sys.stderr)
        return 2
    try:
        alerts = obs_slo.load_alerts(args.path)
        alert = (pick_alert(alerts, slo=args.slo, index=args.alert)
                 if args.round is None else None)
        tag = (alert["slo"] if alert else "manual")
        center = (int(alert.get("epoch") or 0) if alert
                  else (args.round or 0))
        out = args.out or f"incident_{tag}_r{center}.tar"
        manifest = build_bundle(
            args.path, out, slo=args.slo, alert_index=args.alert,
            around_round=args.round, k=args.k)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    print(f"bundle -> {out}")
    print(f"  round window {manifest['window_rounds'][0]}.."
          f"{manifest['window_rounds'][1]}, "
          f"{len(manifest['files'])} member(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
