#!/usr/bin/env python
"""Chaos soak runner: one seeded fault campaign, one replayable artifact.

Runs the process federation (config1 parity geometry by default: 20
clients + 2 standbys + 4 BFT validators + quorum-ack) under a seeded
randomized fault schedule (bflc_demo_tpu.chaos) and writes a JSON
artifact carrying everything needed to replay or triage a failure:

    {seed, profile, schedule, faults executed/skipped, invariant
     verdicts + violations, rounds, final/best accuracy, wall time}

Exit code 0 iff every invariant held AND the accuracy bar was met AND
no armed operator gate tripped: --fail-on-crit turns any CRIT verdict
from the model-quality health plane (obs.health) into a failing run,
--fail-on-slo does the same for SLO burn-rate alerts (obs.slo) — the
verdict-driven operator tooling the observability planes themselves
deliberately never do (they observe; THIS gates).

The headline campaign (TPU_RESULTS.md / tests/test_chaos.py slow soak):

    python tools/chaos_soak.py --rounds 100 --seed 7 --out soak.json

A quick smoke (seeded mini-soak, ~a minute):

    python tools/chaos_soak.py --rounds 8 --clients 4 --standbys 1 \\
        --duration 45 --profile light --min-acc 0
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=7,
                   help="campaign seed (replays the exact schedule)")
    p.add_argument("--profile", default="standard",
                   help="fault profile: light|standard|heavy|heavytail|"
                        "churn, or a '+'-composed blend (e.g. "
                        "heavytail+churn — stragglers AND continuous "
                        "membership turnover, the endurance regime); "
                        "'heavytail' is the pure straggler regime the "
                        "async-aggregation bench runs under")
    p.add_argument("--async-buffer", type=int, default=0,
                   help="run the soak in async buffered-aggregation "
                        "mode (--async-buffer K; 0 = synchronous)")
    p.add_argument("--reseat-every", type=int, default=0,
                   help="async committee re-election period R: every "
                        "R-th buffered drain reseats the committee from "
                        "the drained window's median-score ranking "
                        "(ProtocolConfig.async_reseat_every; needs "
                        "--async-buffer; 0 = frozen committee)")
    p.add_argument("--progress-every", type=float, default=30.0,
                   help="long-horizon mode: write <out>.progress.json "
                        "every N seconds mid-run (last committed round, "
                        "accuracy, faults fired) so a multi-thousand-"
                        "round soak is inspectable while it runs; "
                        "0 = off")
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--clients", type=int, default=20)
    p.add_argument("--standbys", type=int, default=2)
    p.add_argument("--validators", type=int, default=4)
    p.add_argument("--quorum", type=int, default=1)
    p.add_argument("--duration", type=float, default=0.0,
                   help="fault-window length in seconds "
                        "(0 = half the timeout)")
    p.add_argument("--timeout", type=float, default=2400.0)
    p.add_argument("--min-acc", type=float, default=0.92,
                   help="final-accuracy bar (config1 parity: 0.92)")
    p.add_argument("--out", default="",
                   help="artifact path (default chaos_soak_<seed>.json)")
    p.add_argument("--wal", default="", help="WAL path (enables the "
                   "torn-write faults); default: a temp file")
    p.add_argument("--telemetry-dir", default="",
                   help="fleet telemetry dir (metrics.jsonl timeline + "
                        "per-role flight dumps; default "
                        "<out>.telemetry/); render the post-mortem with "
                        "tools/fleet_top.py --timeline")
    p.add_argument("--no-telemetry", action="store_true",
                   help="disable the telemetry plane")
    p.add_argument("--fail-on-crit", action="store_true",
                   help="exit nonzero when the model-quality health "
                        "plane issued any CRIT round verdict "
                        "(obs.health) — the verdict-driven operator "
                        "gate; requires telemetry")
    p.add_argument("--fail-on-slo", action="store_true",
                   help="exit nonzero when the SLO engine raised any "
                        "burn-rate alert (obs.slo alerts.jsonl); "
                        "requires telemetry")
    p.add_argument("--fail-on-recompile-storm", action="store_true",
                   help="exit nonzero when the device plane recorded "
                        "any CRIT recompile-storm verdict (obs.device "
                        "*.device.jsonl) — post-warmup steady state "
                        "must not recompile; requires telemetry")
    p.add_argument("--notify-cmd", default="",
                   help="operator command the SLO engine spawns PER "
                        "alert with the alerts.jsonl record on stdin "
                        "(obs.slo; e.g. a curl webhook one-liner) — "
                        "failure-isolated and counted")
    p.add_argument("--rederive", default="off",
                   choices=["off", "shard", "full"],
                   help="validator re-derivation plane mode "
                        "(bflc_demo_tpu.rederive): validators refuse "
                        "commits whose model hash they cannot "
                        "reproduce; blob-unavailability under chaos "
                        "degrades to counted skips")
    p.add_argument("--verbose", action="store_true", default=True)
    p.add_argument("--quiet", dest="verbose", action="store_false")
    args = p.parse_args(argv)

    from bflc_demo_tpu.chaos.schedule import PROFILES
    parts = [pt for pt in str(args.profile).split("+") if pt]
    unknown = [pt for pt in parts if pt not in PROFILES]
    if unknown or not parts:
        p.error(f"unknown profile part(s) {unknown or [args.profile]}: "
                f"choose from {sorted(PROFILES)} or compose with '+'")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.notify_cmd:
        # the driver-side SLO engine reads it at arming (obs.slo)
        os.environ["BFLC_SLO_NOTIFY_CMD"] = args.notify_cmd
    import numpy as np

    from bflc_demo_tpu.data import load_occupancy, iid_shards
    from bflc_demo_tpu.client.process_runtime import \
        run_federated_processes
    from bflc_demo_tpu.protocol.constants import ProtocolConfig

    # config1 parity geometry, scaled to --clients when smaller fleets
    # are requested (the protocol genome scales like eval.configs does)
    n = args.clients
    cfg = (ProtocolConfig() if n == 20 else ProtocolConfig(
        client_num=n, comm_count=max(2, n // 5),
        aggregate_count=max(2, n // 4),
        needed_update_count=max(2, n // 2))).validate()
    if args.async_buffer or args.reseat_every:
        import dataclasses
        cfg = dataclasses.replace(
            cfg, async_buffer=args.async_buffer,
            async_reseat_every=args.reseat_every).validate()
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(np.asarray(xtr), np.asarray(ytr), cfg.client_num)

    wal = args.wal
    if not wal:
        import tempfile
        wal = os.path.join(tempfile.mkdtemp(prefix="bflc-soak-"),
                           "writer.wal")

    out = args.out or f"chaos_soak_{args.seed}.json"
    telemetry_dir = "" if args.no_telemetry else (
        args.telemetry_dir or out + ".telemetry")

    t0 = time.time()
    failure = ""
    res = None
    stop_progress = None
    if args.progress_every > 0 and telemetry_dir:
        # long-horizon inspectability: a sidecar thread tails the run's
        # own telemetry stream and rewrites <out>.progress.json
        # atomically — `watch cat soak.json.progress.json` mid-campaign
        import threading
        stop_progress = threading.Event()

        def _progress_loop():
            while not stop_progress.wait(args.progress_every):
                _write_progress(out, telemetry_dir, t0, args)

        threading.Thread(target=_progress_loop, daemon=True).start()
    try:
        res = run_federated_processes(
            "make_softmax_regression", shards, (np.asarray(xte),
                                                np.asarray(yte)),
            cfg, rounds=args.rounds,
            standbys=args.standbys, quorum=args.quorum,
            bft_validators=args.validators, wal_path=wal,
            timeout_s=args.timeout,
            chaos_seed=args.seed, chaos_profile=args.profile,
            chaos_duration_s=(args.duration or None),
            telemetry_dir=telemetry_dir,
            rederive=args.rederive,
            verbose=args.verbose)
    except Exception as e:              # noqa: BLE001 — the artifact must
        # record the failure mode; triage replays by seed
        failure = f"{type(e).__name__}: {e}"
    finally:
        if stop_progress is not None:
            stop_progress.set()
            _write_progress(out, telemetry_dir, t0, args, final=True)

    report = dict(res.chaos_report or {}) if res is not None else {}
    violations = report.get("violations", [])
    final_acc = res.final_accuracy if res is not None else 0.0
    gates = operator_gates(telemetry_dir,
                           fail_on_crit=args.fail_on_crit,
                           fail_on_slo=args.fail_on_slo,
                           fail_on_storm=args.fail_on_recompile_storm)
    artifact = {
        "seed": args.seed,
        "profile": args.profile,
        "geometry": {"clients": cfg.client_num,
                     "standbys": args.standbys,
                     "validators": args.validators,
                     "quorum": args.quorum, "rounds": args.rounds,
                     "async_buffer": cfg.async_buffer,
                     "async_reseat_every": cfg.async_reseat_every},
        "wall_time_s": round(time.time() - t0, 1),
        "failure": failure,
        "rounds_completed": (res.rounds_completed if res else 0),
        "final_accuracy": round(final_acc, 4),
        "best_accuracy": round(res.best_accuracy(), 4) if res else 0.0,
        "min_acc_bar": args.min_acc,
        "chaos": report,
        "telemetry": (res.telemetry_report
                      if res is not None else None),
        "gates": gates,
    }
    ok = (not failure and not violations and final_acc >= args.min_acc
          and not gates["failures"])
    artifact["verdict"] = "PASS" if ok else "FAIL"

    with open(out, "w") as fh:
        json.dump(artifact, fh, indent=2)
    print(json.dumps({k: v for k, v in artifact.items()
                      if k not in ("chaos",)}, indent=2))
    print(f"artifact -> {out}")
    if telemetry_dir:
        print(f"telemetry -> {telemetry_dir} (post-mortem: python "
              f"tools/fleet_top.py {telemetry_dir} --timeline)")
    if violations:
        print("INVARIANT VIOLATIONS:", *violations, sep="\n  ")
    for g in gates["failures"]:
        print(f"OPERATOR GATE FAILED: {g}")
    return 0 if ok else 1


def _write_progress(out: str, telemetry_dir: str, t0: float, args,
                    final: bool = False) -> None:
    """One atomic progress snapshot off the run's own telemetry stream
    (tmp-then-rename — a reader never sees a torn file).  Failure-
    isolated: a torn/absent stream yields a sparse record, never an
    exception into the soak driver."""
    prog = {"t": time.time(), "elapsed_s": round(time.time() - t0, 1),
            "seed": args.seed, "profile": args.profile,
            "rounds_target": args.rounds, "final": final}
    try:
        from bflc_demo_tpu.obs.collector import load_timeline
        recs = load_timeline(os.path.join(telemetry_dir,
                                          "metrics.jsonl"))
        commits = [r for r in recs if r.get("type") == "note"
                   and r.get("name") == "round_commit"]
        if commits:
            prog["last_round"] = commits[-1].get("epoch")
            prog["last_acc"] = commits[-1].get("acc")
        prog["faults_fired"] = sum(1 for r in recs
                                   if r.get("type") == "fault"
                                   and r.get("executed"))
        prog["churn_events"] = sum(
            1 for r in recs if r.get("type") == "fault"
            and r.get("kind") in ("retire", "join") and r.get("executed"))
    except Exception:       # noqa: BLE001 — inspectability must never
        pass                # take down the campaign it watches
    path = out + ".progress.json"
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(prog, fh, indent=2)
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass


def operator_gates(telemetry_dir: str, *, fail_on_crit: bool = False,
                   fail_on_slo: bool = False,
                   fail_on_storm: bool = False) -> dict:
    """Verdict-gated operations (the ROADMAP 'verdict-driven operator
    tooling' item): turn the run's health verdicts (obs.health), SLO
    burn-rate alerts (obs.slo) and device recompile-storm verdicts
    (obs.device) into exit-code evidence.  Enforcement lives HERE,
    outside the protocol — the observability planes themselves gate
    nothing (PARITY.md).  Returns {crit_rounds, slo_alerts,
    storm_rounds, failures}; `failures` is non-empty iff an armed gate
    tripped.  Drilled in tier-1 with a scripted attacker
    (tests/test_forensics.py)."""
    gates: dict = {"crit_rounds": [], "slo_alerts": [],
                   "storm_rounds": [], "failures": []}
    if not telemetry_dir or not os.path.isdir(telemetry_dir):
        if fail_on_crit or fail_on_slo or fail_on_storm:
            gates["failures"].append(
                "gating requested but no telemetry dir — run without "
                "--no-telemetry")
        return gates
    from bflc_demo_tpu.obs.health import load_health_records
    from bflc_demo_tpu.obs.slo import load_alerts
    from bflc_demo_tpu.obs.device import load_device_records
    gates["crit_rounds"] = [
        {"epoch": r.get("epoch"), "role": r.get("role"),
         "flagged": [s["sender"] for s in r.get("senders", [])
                     if s.get("level") == "crit"]}
        for r in load_health_records(telemetry_dir)
        if r.get("verdict") == "crit"]
    gates["slo_alerts"] = [
        {"slo": a.get("slo"), "epoch": a.get("epoch"),
         "value": a.get("value"), "bound": a.get("bound")}
        for a in load_alerts(telemetry_dir)]
    if fail_on_crit and gates["crit_rounds"]:
        gates["failures"].append(
            f"--fail-on-crit: {len(gates['crit_rounds'])} CRIT health "
            f"round(s), first at epoch "
            f"{gates['crit_rounds'][0]['epoch']}")
    if fail_on_slo and gates["slo_alerts"]:
        gates["failures"].append(
            f"--fail-on-slo: {len(gates['slo_alerts'])} SLO alert(s), "
            f"first {gates['slo_alerts'][0]['slo']} at epoch "
            f"{gates['slo_alerts'][0]['epoch']}")
    gates["storm_rounds"] = [
        {"epoch": r.get("epoch"), "role": r.get("role"),
         "families": sorted(f for f, d in
                            (r.get("families") or {}).items()
                            if d.get("level") == "crit")}
        for r in load_device_records(telemetry_dir)
        if r.get("type") == "device_storm"
        and r.get("verdict") == "crit"]
    if fail_on_storm and gates["storm_rounds"]:
        gates["failures"].append(
            f"--fail-on-recompile-storm: "
            f"{len(gates['storm_rounds'])} CRIT storm round(s), first "
            f"at epoch {gates['storm_rounds'][0]['epoch']}")
    return gates


if __name__ == "__main__":
    sys.exit(main())
