#!/usr/bin/env python
"""Operator tool for ledger compaction artifacts (ledger.snapshot).

A live fleet GCs itself (the writer compacts its log/WAL behind every
certified snapshot it emits); this tool covers the OFFLINE half an
operator actually meets: a WAL from a dead or stopped writer, a snapshot
directory of retained artifacts, and the question "how big is this, is
it intact, and can I shrink it without losing certified history?".

    # what is in this journal / directory?
    python tools/ledger_gc.py inspect --wal coordinator.wal \
        --snapshot-dir snaps/writer

    # compact the WAL behind the newest intact snapshot artifact and
    # prune old artifacts (tmp-then-rename; SIGKILL-safe at every step)
    python tools/ledger_gc.py gc --wal coordinator.wal \
        --snapshot-dir snaps/writer --keep 2

    # preview without touching anything
    python tools/ledger_gc.py gc --wal coordinator.wal \
        --snapshot-dir snaps/writer --dry-run

Safety rules the `gc` verb enforces (refusing beats shrinking):
- the snapshot artifact must pass its own integrity checks
  (`read_snapshot_file`: torn/bit-flipped files are skipped, older
  intact artifacts are tried next);
- the artifact's snapshot op must be byte-identical to the op the WAL
  itself holds at that chain position — an artifact from some OTHER
  deployment (or a forged one) can never rewrite a journal;
- the replayed ledger must accept the whole retained tail (a WAL whose
  tail is torn compacts only up to the tear, same recovery semantics as
  `replay_wal`).

The compacted journal is the standard WAL2 format
(`pyledger._write_wal_head`): any python-backend ledger replays it
directly; `iter_wal_ops`/`wal_base` (ledger.tool) read it.
"""

import argparse
import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _wal_stats(path):
    from bflc_demo_tpu.ledger.tool import decode_op, iter_wal_ops, wal_base
    ops = list(iter_wal_ops(path))
    kinds = {}
    for _, op in ops:
        k = decode_op(op).get("op", "?")
        kinds[k] = kinds.get(k, 0) + 1
    return {"path": path, "bytes": os.path.getsize(path),
            "base": wal_base(path), "records": len(ops),
            "first_index": ops[0][0] if ops else None,
            "last_index": ops[-1][0] if ops else None,
            "ops_by_kind": kinds}


def _snapshot_stats(dirpath):
    from bflc_demo_tpu.ledger.snapshot import (list_snapshot_files,
                                               read_snapshot_file)
    out = []
    for p in list_snapshot_files(dirpath):
        row = {"path": p, "bytes": os.path.getsize(p)}
        try:
            meta = read_snapshot_file(p)
            row.update(i=meta["i"], epoch=meta["epoch"],
                       gen=meta["gen"], intact=True,
                       certified=meta.get("cert") is not None)
        except ValueError as e:
            row.update(intact=False, error=str(e))
        out.append(row)
    return out


def cmd_inspect(args) -> int:
    report = {}
    if args.wal:
        try:
            report["wal"] = _wal_stats(args.wal)
        except (ValueError, OSError) as e:
            # a torn journal is a report, not a crash — that is the
            # operator's whole question
            report["wal"] = {"path": args.wal,
                             "error": f"{type(e).__name__}: {e}"}
    if args.snapshot_dir:
        report["snapshots"] = _snapshot_stats(args.snapshot_dir)
    print(json.dumps(report, indent=2))
    return 0


def _replay(path, cfg):
    """Fresh python-backend ledger from a WAL (WAL1 or compacted WAL2);
    returns (ledger, records_applied)."""
    from bflc_demo_tpu.ledger.pyledger import PyLedger
    led = PyLedger(cfg.client_num, cfg.comm_count, cfg.aggregate_count,
                   cfg.needed_update_count, cfg.genesis_epoch)
    applied = led.replay_wal(path)
    return led, applied


def cmd_gc(args) -> int:
    from bflc_demo_tpu.ledger.snapshot import (list_snapshot_files,
                                               prune_snapshots,
                                               read_snapshot_file)
    from bflc_demo_tpu.protocol.constants import ProtocolConfig
    cfg_kw = json.loads(args.cfg) if args.cfg else {}
    cfg = ProtocolConfig(**cfg_kw) if cfg_kw else ProtocolConfig()
    try:
        led, applied = _replay(args.wal, cfg)
    except (RuntimeError, ValueError, OSError) as e:
        print(json.dumps({
            "wal": args.wal, "result": "error",
            "error": f"{type(e).__name__}: {e}",
            "hint": "journal would not replay — wrong --cfg geometry "
                    "for this deployment, or a corrupt file; nothing "
                    "was modified"}, indent=2))
        return 1
    before = os.path.getsize(args.wal)
    report = {"wal": args.wal, "bytes_before": before,
              "records_replayed": applied, "base_before": led.log_base,
              "log_size": led.log_size()}

    # newest artifact that (a) is intact, (b) sits inside the journal's
    # retained range, and (c) holds the SAME op bytes the journal holds
    # at that position — the binding that stops a foreign artifact from
    # rewriting this journal
    chosen = None
    for p in reversed(list_snapshot_files(args.snapshot_dir)):
        try:
            meta = read_snapshot_file(p)
        except ValueError as e:
            report.setdefault("skipped", []).append(
                {"path": p, "reason": str(e)})
            continue
        i = int(meta["i"])
        if not led.log_base <= i < led.log_size():
            report.setdefault("skipped", []).append(
                {"path": p,
                 "reason": f"position {i} outside the journal's retained "
                           f"range [{led.log_base}, {led.log_size()})"})
            continue
        op = meta["op"]
        op_b = bytes.fromhex(op) if isinstance(op, str) else bytes(op)
        if led.log_op(i) != op_b:
            report.setdefault("skipped", []).append(
                {"path": p,
                 "reason": f"artifact op at {i} does not match the "
                           f"journal's op (foreign or forged artifact)"})
            continue
        chosen = (p, meta)
        break
    if chosen is None:
        report["result"] = "nothing to do: no usable snapshot artifact"
        print(json.dumps(report, indent=2))
        return 1
    path, meta = chosen
    i = int(meta["i"])
    report["snapshot"] = {"path": path, "i": i, "epoch": meta["epoch"]}
    dropped = i + 1 - led.log_base
    report["records_dropped"] = dropped
    if args.dry_run:
        report["result"] = f"dry-run: would drop {dropped} records " \
                           f"behind snapshot@{i}"
        print(json.dumps(report, indent=2))
        return 0
    led.gc_prefix(i + 1, bytes(meta["state"]))
    led.save_wal(args.wal)              # tmp-then-rename, SIGKILL-safe
    pruned = prune_snapshots(args.snapshot_dir, args.keep)
    report.update(bytes_after=os.path.getsize(args.wal),
                  base_after=led.log_base, artifacts_pruned=pruned,
                  result="ok")
    print(json.dumps(report, indent=2))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="verb", required=True)
    pi = sub.add_parser("inspect", help="report WAL/snapshot-dir state")
    pi.add_argument("--wal", default="")
    pi.add_argument("--snapshot-dir", default="")
    pg = sub.add_parser("gc", help="compact a WAL behind the newest "
                                   "matching snapshot artifact")
    pg.add_argument("--wal", required=True)
    pg.add_argument("--snapshot-dir", required=True)
    pg.add_argument("--keep", type=int, default=2,
                    help="snapshot artifacts to retain (default 2)")
    pg.add_argument("--dry-run", action="store_true")
    pg.add_argument("--cfg", default="",
                    help="ProtocolConfig overrides as JSON (the journal "
                         "replays under this geometry; default preset)")
    args = p.parse_args(argv)
    return cmd_inspect(args) if args.verb == "inspect" else cmd_gc(args)


if __name__ == "__main__":
    sys.exit(main())
