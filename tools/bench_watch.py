"""Opportunistic TPU evidence runner.

The axon tunnel to the TPU is intermittent; the driver-run `bench.py` at
round end may land in a window where the chip is unreachable.  This
watcher closes that gap: it loops, probing the chip cheaply, and whenever
the probe passes it captures the FULL on-chip evidence battery, in value
order (a tunnel window can close at any moment — take the headline
first):

  1. `python bench.py` — headline + MFU, snapshotted to BENCH_LATEST.json
     (a later chip-less `bench.py` replays it, labelled `cached: true` +
     `captured_at`); the line carries every `extra.*` axis, including
     the REDUCTION SPEC v2 `extra.blocked_agg` blocks x N sweep with
     its sharded-model leg and hash-equality verdict;
  2. `tools/tpu_validate.py` — native Mosaic compile + timing of the
     Pallas flash kernels (fwd, blockwise bwd, streaming-carry);
  3. `tools/tpu_flash_train.py` — seq-8192 flash-vs-einsum training;
  4. `tools/tpu_bench_configs.py --configs 0,1,2,3,4,5` — per-config
     round times + MFU column (the longest stage, so it runs last).
Stages 2-4 append to TPU_RESULTS.md and each run at most once per watch
(re-probing between stages so a mid-battery tunnel drop skips cleanly to
the next window instead of burning the timeout).

Usage:  python tools/bench_watch.py [--interval 900] [--max-captures 4]
Runs until max-captures on-TPU bench measurements have been taken
(refreshing the snapshot each time) AND the battery completed, then
exits.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _probe_tpu  # noqa: E402 — the cheap 150 s gate

BATTERY = [
    ("validate", [sys.executable, "tools/tpu_validate.py",
                  "--out", "TPU_RESULTS.md"], 1800),
    ("flash_train", [sys.executable, "tools/tpu_flash_train.py",
                     "--out", "TPU_RESULTS.md"], 1800),
    ("configs", [sys.executable, "tools/tpu_bench_configs.py",
                 "--configs", "0,1,2,3,4,5", "--out", "TPU_RESULTS.md"],
     3600),
]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=900,
                    help="seconds between attempts")
    ap.add_argument("--max-captures", type=int, default=4)
    args = ap.parse_args()

    captures = 0
    battery_done = set()
    while captures < args.max_captures or len(battery_done) < len(BATTERY):
        t0 = time.time()
        # probe first: when the chip is down, one iteration costs ~2 probe
        # timeouts, not a full throwaway CPU benchmark
        if not _probe_tpu():
            print(f"[bench_watch] {time.strftime('%H:%M:%S')} probe failed; "
                  f"chip unreachable", flush=True)
            time.sleep(max(30.0, args.interval - (time.time() - t0)))
            continue
        if captures < args.max_captures:
            try:
                r = subprocess.run(
                    [sys.executable, os.path.join(REPO, "bench.py")],
                    capture_output=True, text=True, cwd=REPO, timeout=3600)
                line = next((ln for ln in r.stdout.splitlines()
                             if ln.startswith("{")), "")
                rec = json.loads(line) if line else {}
                plat = rec.get("extra", {}).get("platform")
                cached = rec.get("extra", {}).get("cached", False)
                print(f"[bench_watch] {time.strftime('%H:%M:%S')} "
                      f"platform={plat} cached={cached} "
                      f"value={rec.get('value')}", flush=True)
                if plat == "tpu" and not cached:
                    captures += 1
            except (subprocess.TimeoutExpired, ValueError) as e:
                print(f"[bench_watch] bench attempt failed: {e}", flush=True)
        for name, cmd, budget in BATTERY:
            if name in battery_done:
                continue
            if not _probe_tpu():    # tunnel can drop mid-battery
                print(f"[bench_watch] tunnel dropped before {name}; "
                      f"will retry next window", flush=True)
                break
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   cwd=REPO, timeout=budget)
                ok = r.returncode == 0
                print(f"[bench_watch] {time.strftime('%H:%M:%S')} "
                      f"{name}: rc={r.returncode} "
                      f"{(r.stdout or r.stderr).strip()[-200:]}",
                      flush=True)
                if ok:
                    battery_done.add(name)
            except subprocess.TimeoutExpired:
                print(f"[bench_watch] {name} timed out after {budget}s",
                      flush=True)
        elapsed = time.time() - t0
        if captures >= args.max_captures and \
                len(battery_done) >= len(BATTERY):
            break
        time.sleep(max(30.0, args.interval - elapsed))
    print(f"[bench_watch] done: {captures} on-TPU captures, battery: "
          f"{sorted(battery_done)}", flush=True)


if __name__ == "__main__":
    main()
