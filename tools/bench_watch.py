"""Opportunistic TPU bench runner.

The axon tunnel to the TPU is intermittent; the driver-run `bench.py` at
round end may land in a window where the chip is unreachable.  This
watcher closes that gap: it loops, probing the chip cheaply, and whenever
the probe passes it runs `python bench.py` — which snapshots any on-TPU
measurement to BENCH_LATEST.json.  A later chip-less `bench.py` invocation
replays that snapshot (labelled `cached: true` + `captured_at`).

Usage:  python tools/bench_watch.py [--interval 900] [--max-captures 4]
Runs until max-captures on-TPU measurements have been taken (refreshing
the snapshot each time), then exits.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _probe_tpu  # noqa: E402 — the cheap 150 s gate


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=900,
                    help="seconds between attempts")
    ap.add_argument("--max-captures", type=int, default=4)
    args = ap.parse_args()

    captures = 0
    while captures < args.max_captures:
        t0 = time.time()
        # probe first: when the chip is down, one iteration costs ~2 probe
        # timeouts, not a full throwaway CPU benchmark
        if not _probe_tpu():
            print(f"[bench_watch] {time.strftime('%H:%M:%S')} probe failed; "
                  f"chip unreachable", flush=True)
            time.sleep(max(30.0, args.interval - (time.time() - t0)))
            continue
        try:
            r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                               capture_output=True, text=True, cwd=REPO,
                               timeout=3600)
            line = next((ln for ln in r.stdout.splitlines()
                         if ln.startswith("{")), "")
            rec = json.loads(line) if line else {}
            plat = rec.get("extra", {}).get("platform")
            cached = rec.get("extra", {}).get("cached", False)
            print(f"[bench_watch] {time.strftime('%H:%M:%S')} platform={plat} "
                  f"cached={cached} value={rec.get('value')}", flush=True)
            if plat == "tpu" and not cached:
                captures += 1
        except (subprocess.TimeoutExpired, ValueError) as e:
            print(f"[bench_watch] attempt failed: {e}", flush=True)
        if captures >= args.max_captures:
            break
        elapsed = time.time() - t0
        time.sleep(max(30.0, args.interval - elapsed))
    print(f"[bench_watch] done: {captures} on-TPU captures", flush=True)


if __name__ == "__main__":
    main()
