#!/usr/bin/env python
"""trace_report: reassemble causal op traces and print round critical
paths (obs.trace).

Input is a telemetry directory (a federation run with
`telemetry_dir=... , trace_sample>0`, or `--telemetry-dir/--trace-sample`
from the CLI): every role flushed its spans to `<role>.spans.jsonl`
there, and `metrics.jsonl` (when present) supplies chaos fault markers
and the writer's upload-lag histogram for cross-checking.

Per round the report answers *why was this round slow*:

- the **critical path**: every instant of the round attributed to the
  deepest span active then (segment sums equal the round wall time by
  construction — attribution, not estimation);
- the **straggler ranking**: each client's upload admission lag behind
  the round's first upload, read off the traces and cross-checked
  against the writer's `upload_lag_seconds` histogram;
- **fault attribution**: which segment each chaos fault landed in.

Usage:
    python tools/trace_report.py <telemetry_dir> [--round N] [--json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bflc_demo_tpu.obs import trace as obs_trace            # noqa: E402
from bflc_demo_tpu.obs.collector import load_timeline      # noqa: E402


def _writer_upload_lag(timeline):
    """(count, mean_s, p95ish_s) from the newest scrape's writer
    `upload_lag_seconds` histogram, or None — the metric-side view the
    trace ranking is cross-checked against."""
    for rec in reversed(timeline):
        if rec.get("type") != "scrape":
            continue
        snap = (rec.get("roles") or {}).get("writer")
        if not snap:
            continue
        m = (snap.get("metrics") or {}).get("upload_lag_seconds")
        if not m or not m.get("samples"):
            continue
        s = m["samples"][0]
        count = s.get("count", 0)
        if not count:
            return None
        p95 = None
        thresh = 0.95 * count
        for le, cum in s.get("buckets", {}).items():
            if cum >= thresh:
                p95 = float("inf") if le == "+Inf" else float(le)
                break
        return {"count": count, "mean_s": s.get("sum", 0.0) / count,
                "p95_le_s": p95}
    return None


def build_report(telemetry_dir: str) -> dict:
    """The whole artifact as one dict: per-round reports, across-round
    segment stats, and the metric cross-check."""
    spans = obs_trace.gather_spans(telemetry_dir)
    timeline = load_timeline(os.path.join(telemetry_dir,
                                          "metrics.jsonl"))
    faults = [r for r in timeline if r.get("type") == "fault"]
    reports = obs_trace.round_reports(spans, faults=faults)
    return {
        "telemetry_dir": telemetry_dir,
        "n_spans": len(spans),
        "n_traces": len(obs_trace.assemble_traces(spans)),
        "rounds": reports,
        "segment_stats": obs_trace.segment_stats(reports),
        "writer_upload_lag": _writer_upload_lag(timeline),
    }


def render(report: dict, only_round=None) -> str:
    lines = [f"{report['n_traces']} traces / {report['n_spans']} spans "
             f"from {report['telemetry_dir']}"]
    if not report["rounds"]:
        lines.append("no reassembled rounds (tracing off, sample too "
                     "low, or no spans flushed)")
        return "\n".join(lines)
    for rep in report["rounds"]:
        if only_round is not None and rep["epoch"] != only_round:
            continue
        lines.append(obs_trace.format_round_report(rep))
    stats = sorted(report["segment_stats"].items(),
                   key=lambda kv: -kv[1]["p95_s"])
    lines.append("per-segment totals across rounds (p50/p95):")
    for label, st in stats[:12]:
        lines.append(f"  {label:<32} {st['p50_s']:7.3f}s /"
                     f" {st['p95_s']:7.3f}s  ({st['rounds']} rounds)")
    lag = report.get("writer_upload_lag")
    if lag:
        # the metric-side cross-check of the trace-side straggler
        # ranking: same distribution, independently measured
        p95 = lag["p95_le_s"]
        lines.append(
            f"writer upload_lag_seconds histogram: {lag['count']} "
            f"uploads, mean {lag['mean_s']:.3f}s, p95 bucket <= "
            f"{'inf' if p95 in (None, float('inf')) else f'{p95:.3g}s'}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="telemetry dir holding *.spans.jsonl")
    ap.add_argument("--round", type=int, default=None,
                    help="only this round's critical path")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    args = ap.parse_args(argv)
    path = args.path
    if os.path.isfile(path):
        path = os.path.dirname(os.path.abspath(path))
    if not os.path.isdir(path):
        print(f"no such telemetry dir: {path}", file=sys.stderr)
        return 2
    report = build_report(path)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render(report, only_round=args.round))
    return 0


if __name__ == "__main__":
    sys.exit(main())
