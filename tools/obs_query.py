#!/usr/bin/env python
"""obs_query: query the unified round timeline of a telemetry dir.

The one-stop forensics view (bflc_demo_tpu.obs.timeline): joins every
artifact stream a run left behind — metrics.jsonl scrapes/faults/notes,
*.health.jsonl verdicts, *.spans.jsonl causal traces, *.flight.jsonl
dumps, alerts.jsonl SLO pages — and answers round questions without
hand-correlating five file formats:

    python tools/obs_query.py <telemetry_dir>              # all rounds
    python tools/obs_query.py <dir> --round 41             # one round,
        # full detail: wall, critical-path partition, health verdict +
        # flagged senders (+ worst leaves), faults in window, alerts
    python tools/obs_query.py <dir> --since 30             # tail rounds
    python tools/obs_query.py <dir> --slo round_latency    # the SLO's
        # alerts with their embedded round context
    python tools/obs_query.py <dir> --role cell-1          # one role's
        # health stream only

Markdown to stdout by default; --json prints machine-readable records;
--out additionally writes the JSON to a file.  Read-only over the
artifacts — this tool renders what the fleet recorded, it gates nothing
(tools/chaos_soak.py --fail-on-crit/--fail-on-slo is the gating half).
"""

import argparse
import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bflc_demo_tpu.obs.timeline import (  # noqa: E402
    RoundTimeline, load_round_timeline)


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "-"


def round_rows(tl: RoundTimeline, rounds: List[int]) -> List[dict]:
    return [tl.round_record(r) for r in rounds]


def render_summary(tl: RoundTimeline, recs: List[dict]) -> str:
    lines = ["# Round forensics timeline", ""]
    verdicts = {"ok": 0, "warn": 0, "crit": 0}
    for rec in recs:
        v = rec.get("health_verdict")
        if v in verdicts:
            verdicts[v] += 1
    lines.append(f"{len(recs)} rounds joined — health ok "
                 f"{verdicts['ok']} / warn {verdicts['warn']} / crit "
                 f"{verdicts['crit']}; {len(tl.alerts)} SLO alert(s); "
                 f"{len(tl.faults)} fault record(s)")
    lines += ["", "| round | wall | health | flagged | faults | "
                  "coverage | acc | genome | alerts |",
              "|---|---|---|---|---|---|---|---|---|"]
    for rec in recs:
        flagged = sum(h.get("flagged", 0)
                      for h in rec["health"].values())
        acc = (rec["commit"] or {}).get("acc")
        cov = rec.get("scrape_coverage")
        alerts = ", ".join(a["slo"] for a in rec["alerts"]) or "-"
        # closed-loop knob transitions this round committed
        genome = "-"
        gs = rec.get("genome_updates") or []
        if gs:
            g = gs[-1]
            genome = (f"d {g.get('old_density'):g}->"
                      f"{g.get('new_density'):g}"
                      if g.get("old_density") != g.get("new_density")
                      else "held")
        lines.append(
            f"| {rec['epoch']} | {_fmt_s(rec.get('wall_s'))} "
            f"| {(rec.get('health_verdict') or '-').upper()} "
            f"| {flagged} | {len(rec['faults'])} "
            f"| {f'{cov:.0%}' if cov is not None else '-'} "
            f"| {f'{acc:.4f}' if acc is not None else '-'} "
            f"| {genome} | {alerts} |")
    return "\n".join(lines)


def render_round(rec: dict) -> str:
    r = rec["epoch"]
    lines = [f"# Round {r} forensics", ""]
    lines.append(f"wall {_fmt_s(rec.get('wall_s'))}  "
                 f"health {(rec.get('health_verdict') or '?').upper()}  "
                 f"scrapes {rec.get('scrapes')}"
                 + ("  (epoch-stamped)" if rec.get("epoch_stamped")
                    else ""))
    commit = rec.get("commit") or {}
    if commit:
        lines.append("commit: " + "  ".join(
            f"{k}={v}" for k, v in sorted(commit.items())))
    if rec.get("committee"):
        lines.append(
            "committee: " + ", ".join(rec["committee"])
            + ("  (reseated this round)" if rec.get("reseat") else ""))
    # closed-loop compression: the certified genome-update op(s) this
    # round's commit proposed — old -> new knobs plus the telemetry
    # the fixed rule decided on (what every validator re-derived)
    for g in rec.get("genome_updates", []) or ():
        parts = [f"genome update @ commit {g.get('commit_epoch')}:"]
        if g.get("old_density") != g.get("new_density"):
            parts.append(f"density {g.get('old_density'):g} -> "
                         f"{g.get('new_density'):g}")
        if g.get("old_staleness") != g.get("new_staleness"):
            parts.append(f"staleness {g.get('old_staleness')} -> "
                         f"{g.get('new_staleness')}")
        if len(parts) == 1:
            parts.append("knobs held")
        parts.append(f"(disagree={g.get('disagreement'):.3g} "
                     f"drift={g.get('drift'):.3g} "
                     f"norm={g.get('update_norm'):.3g})")
        lines.append(" ".join(parts))
    tr = rec.get("trace")
    if tr:
        lines += ["", "## Critical path (partition of round wall)", ""]
        wall = tr["wall_s"]
        lines.append(f"trace wall {wall:.3f}s, attributed "
                     f"{tr['covered_frac']:.0%}")
        for label, dur in tr["segments"]:
            lines.append(f"- {label}: {dur:.3f}s "
                         f"({dur / wall:.0%})" if wall else
                         f"- {label}: {dur:.3f}s")
        if tr.get("stragglers"):
            worst = ", ".join(f"{role} +{lag:.3f}s"
                              for role, lag in tr["stragglers"][:5])
            lines.append(f"stragglers: {worst}")
        for f in tr.get("fault_segments", []):
            lines.append(f"fault {f.get('kind')} {f.get('target')} "
                         f"-> landed in {f.get('landed_in')}")
    if rec.get("faults"):
        lines += ["", "## Faults in window", ""]
        for f in rec["faults"]:
            lines.append(f"- {f.get('kind', '?')} "
                         f"{f.get('target', '')} "
                         f"(t={f.get('t', 0):.3f})")
    dev = rec.get("device")
    if dev:
        lines += ["", "## Device (XLA compile / memory)", ""]
        delta = dev.get("recompiles_delta")
        by_fam = dev.get("compiles_by_family") or {}
        fams = ", ".join(f"{f}={int(v)}" for f, v in
                         sorted(by_fam.items()) if v) or "-"
        if delta is None:
            lines.append("fresh compiles - (warmup round)")
        else:
            lines.append(f"fresh compiles {int(delta)}  "
                         f"by family: {fams}")
        mem = dev.get("mem_peak_bytes")
        frac = dev.get("mem_frac")
        if mem is not None or frac is not None:
            lines.append(
                "mem peak "
                + (f"{mem / 1e6:.1f}MB" if mem is not None else "-")
                + (f"  ({frac:.0%} of ceiling)"
                   if frac is not None else ""))
        for ev in dev.get("compile_events", [])[:8]:
            lines.append(
                f"- compile {ev.get('family')}: "
                f"{ev.get('seconds', 0):.3f}s"
                + (f"  {ev.get('flops', 0):.3g} FLOPs" if ev.get("flops")
                   else "")
                + ("  (estimated)" if ev.get("estimated") else ""))
        storm = dev.get("storm")
        if storm:
            worst = ", ".join(
                f"{f} z={d.get('z')}" for f, d in
                sorted((storm.get("families") or {}).items())
                if d.get("level") != "ok")
            lines.append(f"storm verdict "
                         f"{(storm.get('verdict') or 'ok').upper()}"
                         + (f" — {worst}" if worst else ""))
        for xp in dev.get("xprof", []):
            lines.append(f"- xprof capture ({xp.get('trigger', '?')}) "
                         f"-> {xp.get('dir', '?')}")
    for role, h in sorted(rec.get("health", {}).items()):
        lines += ["", f"## Health — {role}: "
                      f"{h.get('verdict', 'ok').upper()}", ""]
        lines.append(f"update_norm {h.get('update_norm')}  drift "
                     f"{h.get('model_drift')}  score med/IQR/disagree "
                     f"{h.get('score_median')}/{h.get('score_iqr')}/"
                     f"{h.get('score_disagreement')}")
        for s in h.get("senders", []):
            if s.get("level", "ok") == "ok":
                continue
            line = (f"- {s['sender']}: {s['level'].upper()} "
                    f"({', '.join(s.get('reasons', []))}) "
                    f"l2={s.get('l2')} cos={s.get('cos')} "
                    f"z={s.get('z')}")
            lines.append(line)
            for leaf in s.get("leaves", []) or ():
                lines.append(
                    f"    worst leaf {leaf['key']}: "
                    f"l2 {leaf['l2']} vs med {leaf['l2_med']} "
                    f"({leaf['ratio']}x)"
                    + (f" cos {leaf['cos']}"
                       if leaf.get("cos") is not None else ""))
    if rec.get("alerts"):
        lines += ["", "## SLO alerts", ""]
        for a in rec["alerts"]:
            lines.append(
                f"- {a['slo']}: {a['signal']}={a.get('value')} vs "
                f"{a['op']} {a['bound']} (burn fast/slow "
                f"{a.get('burn_fast')}/{a.get('burn_slow')})")
    return "\n".join(lines)


def render_slo(tl: RoundTimeline, name: str) -> str:
    alerts = [a for a in tl.alerts if a.get("slo") == name]
    lines = [f"# SLO alerts — {name}", ""]
    if not alerts:
        lines.append("(no alerts for this objective)")
        return "\n".join(lines)
    for a in alerts:
        lines.append(f"## round {a.get('epoch')}: "
                     f"{a['signal']}={a.get('value')} vs {a['op']} "
                     f"{a['bound']} (burn {a.get('burn_fast')}/"
                     f"{a.get('burn_slow')}, budget {a.get('budget')})")
        ctx = a.get("context") or {}
        if ctx:
            lines.append(f"   wall {_fmt_s(ctx.get('wall_s'))}  health "
                         f"{(ctx.get('health_verdict') or '-').upper()}"
                         f"  faults {len(ctx.get('faults', []))}")
        lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="telemetry dir (the FleetCollector's "
                                 "artifact directory)")
    ap.add_argument("--round", type=int, default=None,
                    help="full forensic detail for one round")
    ap.add_argument("--role", default="",
                    help="restrict health streams to one role")
    ap.add_argument("--slo", default="",
                    help="show a named objective's alerts")
    ap.add_argument("--since", type=int, default=None,
                    help="only rounds >= this epoch")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable records instead of markdown")
    ap.add_argument("--out", default="",
                    help="also write the JSON records to this file")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.path):
        print(f"no such telemetry dir: {args.path}", file=sys.stderr)
        return 2
    tl = load_round_timeline(args.path)
    rounds = tl.rounds()
    if args.since is not None:
        rounds = [r for r in rounds if r >= args.since]
    if args.round is not None:
        rounds = [r for r in rounds if r == args.round]
        if not rounds:
            print(f"round {args.round} not present in any stream "
                  f"under {args.path}", file=sys.stderr)
            return 2
    if not rounds and not args.slo:
        print(f"no joinable rounds under {args.path} (telemetry "
              f"disabled, or empty run)", file=sys.stderr)
        return 2
    recs = round_rows(tl, rounds)
    if args.role:
        for rec in recs:
            rec["health"] = {role: h
                             for role, h in rec["health"].items()
                             if role == args.role}
    payload = {"dir": args.path, "rounds": recs,
               "alerts": ([a for a in tl.alerts
                           if a.get("slo") == args.slo]
                          if args.slo else tl.alerts)}
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
    if args.json:
        print(json.dumps(payload, indent=2))
    elif args.slo:
        print(render_slo(tl, args.slo))
    elif args.round is not None:
        print(render_round(recs[0]))
    else:
        print(render_summary(tl, recs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
