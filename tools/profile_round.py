"""Run ONE instrumented federation round and print the tracing breakdown.

Future perf PRs should start from data, not vibes: this tool stands up the
full BFT control plane IN ONE PROCESS (writer + 4 commit validators,
thread-served, exactly the tests' topology), arms the telemetry plane
(obs.metrics + utils.tracing.PROC), drives a complete config-1-shaped
protocol round through the real socket path — register, uploads,
committee scores, aggregation, certification — and prints where the time
went.  Since PR 4 the numbers arrive the way every fleet consumer gets
them: a FleetCollector scrape of the `telemetry` wire RPC (the snapshot
carries the tracer's cost categories), not bespoke in-process reads:

    wire      frame send/recv on every socket hop
    crypto    Ed25519 sign/verify (the one chokepoint, comm.identity)
    validate  validator-side re-execution + co-signing (comm.bft)
    certify   writer-side certificate assembly round-trips
    aggregate on-coordinator FedAvg + commit

Because every role shares the process, the tracer sees all sides at once;
note that shared-process accounting also means the verify memo collapses
the validators' repeated client-tag checks — the per-process federation
numbers live in `eval.benchmarks.federation_config1`.

Usage:  python tools/profile_round.py [--clients N] [--legacy]
        --legacy pins the pre-PR control plane (sequential certification,
        naive Ed25519, hex-JSON frames) by re-exec'ing with
        BFLC_CONTROL_PLANE_LEGACY=1 so import-time switches apply.
"""

import argparse
import hashlib
import os
import struct
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
# sibling tools (fleet_top's snapshot helpers) importable regardless of
# how this script was launched
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _reexec_legacy() -> None:
    env = dict(os.environ, BFLC_CONTROL_PLANE_LEGACY="1",
               JAX_PLATFORMS="cpu")
    args = [a for a in sys.argv if a != "--legacy"]
    os.execve(sys.executable, [sys.executable] + args, env)


def profile_hier(args) -> None:
    """One hierarchical round, fully in-process: a root LedgerServer
    (cell registry + validator quorum) + N CellAggregatorServer threads,
    member wallet-clients driving each cell's round over real sockets.
    Prints the per-cell telemetry rows (admitted count, partial-sum
    latency, cell-aggregate root-certify latency) off the same
    FleetCollector scrape the fleet tools use."""
    import hashlib
    import struct
    import time

    import numpy as np

    from bflc_demo_tpu.comm.bft import ValidatorNode, provision_validators
    from bflc_demo_tpu.comm.identity import Wallet, _op_bytes
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    from bflc_demo_tpu.hier.aggregator import CellAggregatorServer
    from bflc_demo_tpu.hier.cells import (cell_protocol, cell_seed,
                                          plan_cells, root_protocol)
    from bflc_demo_tpu.obs import metrics as obs_metrics
    from bflc_demo_tpu.obs.collector import FleetCollector
    from bflc_demo_tpu.protocol.constants import ProtocolConfig
    from bflc_demo_tpu.utils import tracing
    from bflc_demo_tpu.utils.serialization import pack_pytree

    n = max(args.clients, 2 * args.cells)
    base = ProtocolConfig(client_num=n, comm_count=max(2, n // 4),
                          aggregate_count=2,
                          needed_update_count=max(3, n // 2),
                          learning_rate=0.05, batch_size=16)
    plan = plan_cells(n, cells=args.cells)
    blob0 = pack_pytree({"W": np.zeros((5, 2), np.float32),
                         "b": np.zeros((2,), np.float32)})

    tracing.PROC.enabled = True
    tracing.PROC.reset()
    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "profile"

    agg_wallets = {c: Wallet.from_seed(cell_seed(b"profile-hier", c))
                   for c in range(plan.n_cells)}
    registry = {agg_wallets[c].address: (c, len(plan.members[c]))
                for c in range(plan.n_cells)}
    root_cfg = root_protocol(base, plan.n_cells)
    vwallets, vkeys = provision_validators(args.validators,
                                           b"profile-hier-validators")
    nodes = [ValidatorNode(root_cfg, w, i, validator_keys=vkeys,
                           cell_registry=registry)
             for i, w in enumerate(vwallets)]
    for v in nodes:
        v.start()
    root = LedgerServer(root_cfg, blob0, cell_registry=registry,
                        bft_validators=[(v.host, v.port) for v in nodes],
                        bft_keys=vkeys)
    root.start()
    cells = []
    for c in range(plan.n_cells):
        cc = cell_protocol(base, len(plan.members[c]))
        srv = CellAggregatorServer(cc, blob0, c, agg_wallets[c],
                                   [(root.host, root.port)],
                                   stall_timeout_s=60.0)
        srv.start()
        cells.append(srv)

    def sign(w, kind, epoch, payload):
        return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()

    t_round = time.perf_counter()
    conns = []
    for c, srv in enumerate(cells):
        cc = srv.cfg
        wallets = [Wallet.from_seed(b"profile-hier-member|%d|%d" % (c, i))
                   for i in range(cc.client_num)]
        conn = CoordinatorClient(srv.host, srv.port)
        conns.append(conn)
        for w in wallets:
            r = conn.request("register", addr=w.address,
                             pubkey=w.public_bytes.hex(),
                             tag=sign(w, "register", 0, b""))
            assert r["ok"], r
        committee = set(conn.request("committee")["committee"])
        trainers = [w for w in wallets if w.address not in committee]
        for i, w in enumerate(trainers[: cc.needed_update_count]):
            blob = pack_pytree({"W": np.full((5, 2), 0.1 * (i + 1),
                                             np.float32),
                                "b": np.zeros((2,), np.float32)})
            digest = hashlib.sha256(blob).digest()
            payload = digest + struct.pack("<qd", 10 + i, 1.0)
            r = conn.request("upload", addr=w.address, blob=blob,
                             hash=digest.hex(), n=10 + i, cost=1.0,
                             epoch=0,
                             tag=sign(w, "upload", 0, payload))
            assert r["ok"], r
        n_up = min(cc.needed_update_count, len(trainers))
        for j, w in enumerate([w for w in wallets
                               if w.address in committee]):
            row = [0.5 + 0.01 * (j + u) for u in range(n_up)]
            payload = struct.pack(f"<{n_up}d", *row)
            r = conn.request("scores", addr=w.address, epoch=0,
                             scores=row,
                             tag=sign(w, "scores", 0, payload))
            assert r["ok"] or r.get("status") == "WRONG_EPOCH", r

    probe = CoordinatorClient(root.host, root.port)
    deadline = time.monotonic() + 60.0
    while probe.request("info")["epoch"] < 1:
        if time.monotonic() > deadline:
            raise TimeoutError("root round never committed")
        time.sleep(0.05)
    wall = time.perf_counter() - t_round
    info = probe.request("info")

    coll = FleetCollector(
        {"writer": (root.host, root.port),
         **{f"cell-{c}": (s.host, s.port) for c, s in enumerate(cells)},
         **{f"validator-{i}": (v.host, v.port)
            for i, v in enumerate(nodes)}})
    scrape = coll.scrape(tag="profile_hier")

    for conn in conns:
        conn.close()
    probe.close()
    for s in cells:
        s.close()
    root.close()
    for v in nodes:
        v.close()

    from fleet_top import _role_row
    print(f"one hierarchical round: {n} members in {plan.n_cells} "
          f"cells, {args.validators} validators — root certified "
          f"{info['certified_size']}/{info['log_size']} ops "
          f"(O(cells) per round), wall {wall * 1e3:.0f} ms")
    print(f"telemetry scrape: {scrape['coverage']['answered']}/"
          f"{scrape['coverage']['expected']} roles answered")
    for role in sorted(scrape["roles"]):
        if role.startswith(("cell", "writer")):
            print(_role_row(role, scrape["roles"][role]))


def profile_mesh_agg(args) -> None:
    """In-process meshagg microprofile: N admitted-shaped deltas merged
    by the compiled mesh leg and the host loop (REDUCTION SPEC v1),
    with the differential verdict and the engine telemetry row the
    fleet tools render.  `--mesh-agg N` sets N; `--reduce-blocks B`
    additionally times the REDUCTION SPEC v2 blocked leg at that
    geometry (bytes must equal the v1 legs — the verdict prints)."""
    import hashlib as _hl
    import statistics
    import time as _time

    import numpy as np

    from bflc_demo_tpu.meshagg.engine import ENGINE, flatten_delta
    from bflc_demo_tpu.obs import metrics as obs_metrics
    from bflc_demo_tpu.utils.serialization import pack_entries

    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "profile"

    n = args.mesh_agg
    rng = np.random.default_rng(0)
    shapes = {f"/L{i:02d}": (20, 20) for i in range(24)}
    keys = sorted(shapes)
    g = {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()}
    deltas = [{k: (rng.standard_normal(s) * 0.01).astype(np.float32)
               for k, s in shapes.items()} for _ in range(n)]
    rows = [flatten_delta(d, keys) for d in deltas]
    weights = [float(rng.integers(8, 64)) for _ in range(n)]
    selected = list(range(n))

    t0 = _time.perf_counter()
    out_mesh = ENGINE.aggregate_rows(g, rows, weights, selected, 0.05,
                                     force_leg="mesh")
    compile_s = _time.perf_counter() - t0
    legs = {}
    for leg, run in (
            ("mesh", lambda: ENGINE.aggregate_rows(
                g, rows, weights, selected, 0.05, force_leg="mesh")),
            ("host", lambda: ENGINE.aggregate_flat(
                g, deltas, weights, selected, 0.05,
                force_leg="legacy"))):
        ts = []
        for _ in range(5):
            t1 = _time.perf_counter()
            out = run()
            ts.append(_time.perf_counter() - t1)
        legs[leg] = (statistics.median(ts), out)
    blocks = max(int(getattr(args, "reduce_blocks", 1)), 1)
    if blocks > 1:
        # REDUCTION SPEC v2: the blocked leg at the genome geometry —
        # same bytes, 1/B peak staging, params-shardable on a mesh
        t0 = _time.perf_counter()
        out_blk = ENGINE.aggregate_rows(g, rows, weights, selected,
                                        0.05, force_leg="mesh",
                                        blocks=blocks)
        blk_compile_s = _time.perf_counter() - t0
        ts = []
        for _ in range(5):
            t1 = _time.perf_counter()
            ENGINE.aggregate_rows(g, rows, weights, selected, 0.05,
                                  force_leg="mesh", blocks=blocks)
            ts.append(_time.perf_counter() - t1)
        legs["blocked"] = (statistics.median(ts), out_blk,
                           blk_compile_s)
    h_mesh = _hl.sha256(pack_entries(out_mesh)).hexdigest()
    h_host = _hl.sha256(pack_entries(legs["host"][1])).hexdigest()
    rep = ENGINE.report()
    print(f"meshagg engine: {n} stacked deltas x "
          f"{sum(int(np.prod(s)) for s in shapes.values())} params "
          f"(24 leaves), spec v{rep['spec_version']}")
    print(f"mesh leg (staged rows): {legs['mesh'][0] * 1e3:8.2f} ms   "
          f"(first call incl. compile {compile_s * 1e3:.0f} ms)")
    print(f"host loop (pre-engine): {legs['host'][0] * 1e3:8.2f} ms   "
          f"speedup {legs['host'][0] / max(legs['mesh'][0], 1e-9):.2f}x")
    if blocks > 1:
        blk_med, out_blk, blk_compile_s = legs["blocked"]
        h_blk = _hl.sha256(pack_entries(out_blk)).hexdigest()
        print(f"blocked leg (B={blocks:4d}): {blk_med * 1e3:8.2f} ms   "
              f"(first call incl. compile {blk_compile_s * 1e3:.0f} ms)"
              f"   vs v1 mesh {legs['mesh'][0] / max(blk_med, 1e-9):.2f}x"
              f"   bytes=={'OK' if h_blk == h_host else 'DIVERGED'}")
    print(f"certified bytes identical: {h_mesh == h_host}   "
          f"selfcheck={rep['selfcheck']}   "
          f"programs compiled={rep['compile_total']}   "
          f"last_blocks={rep['last_blocks']}")
    from fleet_top import _role_row
    print(_role_row("profile", obs_metrics.REGISTRY.snapshot()))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--validators", type=int, default=4)
    ap.add_argument("--cells", type=int, default=0,
                    help="profile the hierarchical tier: N in-process "
                         "cell aggregators submitting certified "
                         "cell-aggregate ops to a root quorum")
    ap.add_argument("--legacy", action="store_true",
                    help="profile the pre-PR control plane")
    ap.add_argument("--snapshot-interval", type=int, default=0,
                    help="emit a certified snapshot every N rounds and "
                         "print the compaction row (0 = off)")
    ap.add_argument("--async-buffer", type=int, default=0,
                    help="profile ONE async buffered aggregation "
                         "instead of a synchronous round: K staleness-"
                         "tagged admissions trigger the commit and the "
                         "async telemetry row (buffer depth, staleness "
                         "histogram, aggregations) prints off the same "
                         "scrape (0 = sync round)")
    ap.add_argument("--mesh-agg", type=int, default=0, metavar="N",
                    help="profile the meshagg batched-aggregation "
                         "engine instead of a socket round: merge N "
                         "stacked deltas through the compiled mesh "
                         "leg AND the host loop, print per-leg "
                         "latency, the hash-equality verdict and the "
                         "telemetry row (0 = off)")
    ap.add_argument("--reduce-blocks", type=int, default=1, metavar="B",
                    help="with --mesh-agg: additionally profile the "
                         "REDUCTION SPEC v2 blocked leg at this block "
                         "count (byte-equality verdict prints; 1 = "
                         "v1 only)")
    ap.add_argument("--delta-density", type=float, default=1.0,
                    help="run the round with sparse top-k uploads at "
                         "this density (utils.serialization "
                         "pack_sparse; 1.0 = dense) and print the "
                         "sparse encode/decode telemetry row")
    args = ap.parse_args()
    if args.legacy and not os.environ.get("BFLC_CONTROL_PLANE_LEGACY"):
        _reexec_legacy()
    if args.mesh_agg:
        profile_mesh_agg(args)
        return
    if args.cells:
        profile_hier(args)
        return

    import numpy as np

    from bflc_demo_tpu.comm.bft import ValidatorNode, provision_validators
    from bflc_demo_tpu.comm.identity import (ED25519_BACKEND, _op_bytes,
                                             provision_wallets)
    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    from bflc_demo_tpu.obs import metrics as obs_metrics
    from bflc_demo_tpu.obs.collector import FleetCollector
    from bflc_demo_tpu.protocol.constants import ProtocolConfig
    from bflc_demo_tpu.utils import tracing
    from bflc_demo_tpu.utils.serialization import pack_pytree, pack_sparse

    n = args.clients
    density = float(args.delta_density)
    cfg = ProtocolConfig(client_num=n, comm_count=max(2, n // 4),
                         aggregate_count=2,
                         needed_update_count=max(3, n // 2),
                         learning_rate=0.05, batch_size=16,
                         delta_density=density,
                         async_buffer=max(args.async_buffer, 0)).validate()

    def pack_delta(tree):
        # the scripted uploads use the same encode policy a real
        # client would (sparse when the density arms it)
        return (pack_sparse(tree, density) if density < 1.0
                else pack_pytree(tree))
    wallets, _ = provision_wallets(n, b"profile-round-seed")
    vwallets, vkeys = provision_validators(args.validators,
                                           b"profile-round-validators")
    blob0 = pack_pytree({"W": np.zeros((5, 2), np.float32),
                         "b": np.zeros((2,), np.float32)})

    tracing.PROC.enabled = True
    tracing.PROC.reset()
    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "profile"
    nodes = [ValidatorNode(cfg, w, i, validator_keys=vkeys)
             for i, w in enumerate(vwallets)]
    for v in nodes:
        v.start()
    snap_dir = ""
    if args.snapshot_interval:
        import tempfile
        snap_dir = tempfile.mkdtemp(prefix="bflc-profile-snap-")
    server = LedgerServer(cfg, blob0,
                          bft_validators=[(v.host, v.port) for v in nodes],
                          bft_keys=vkeys,
                          snapshot_interval=args.snapshot_interval,
                          snapshot_dir=snap_dir)
    server.start()
    client = CoordinatorClient(server.host, server.port)

    def sign(w, kind, epoch, payload):
        return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()

    t_round = time.perf_counter()
    for w in wallets:
        r = client.request("register", addr=w.address,
                           pubkey=w.public_bytes.hex(),
                           tag=sign(w, "register", 0, b""))
        assert r["ok"], r
    committee = set(client.request("committee")["committee"])
    trainers = [w for w in wallets if w.address not in committee]
    if args.async_buffer:
        # one async aggregation: K-1 admissions, committee scores over
        # the live buffer (no epoch gate), then the K-th admission
        # triggers the staleness-weighted commit inside its own ack
        from bflc_demo_tpu.ledger.base import ascores_sign_payload

        def aupload(i, w):
            blob = pack_delta({"W": np.full((5, 2), 0.1 * (i + 1),
                                            np.float32),
                               "b": np.zeros((2,), np.float32)})
            digest = hashlib.sha256(blob).digest()
            payload = digest + struct.pack("<qd", 10 + i, 1.0)
            return client.request(
                "aupload", addr=w.address, blob=blob,
                hash=digest.hex(), n=10 + i, cost=1.0, base_epoch=0,
                tag=sign(w, "aupload", 0, payload))

        k = min(args.async_buffer, len(trainers))
        for i, w in enumerate(trainers[: k - 1]):
            assert aupload(i, w)["ok"]
        au = client.request("aupdates")
        pairs = [(u["aseq"], 0.5 + 0.01 * u["aseq"])
                 for u in au["updates"]]
        for w in [w for w in wallets if w.address in committee]:
            if not pairs:
                break
            r = client.request(
                "ascores", addr=w.address,
                pairs=[[a, s] for a, s in pairs],
                tag=w.sign(_op_bytes("ascores", w.address, 0,
                                     ascores_sign_payload(pairs))).hex())
            assert r["ok"], r
        r = aupload(k - 1, trainers[k - 1])
        assert r["ok"] and r["epoch"] == 1, r
    else:
        for i, w in enumerate(trainers[: cfg.needed_update_count]):
            blob = pack_delta({"W": np.full((5, 2), 0.1 * (i + 1),
                                            np.float32),
                               "b": np.zeros((2,), np.float32)})
            digest = hashlib.sha256(blob).digest()
            payload = digest + struct.pack("<qd", 10 + i, 1.0)
            r = client.request("upload", addr=w.address, blob=blob,
                               hash=digest.hex(), n=10 + i, cost=1.0,
                               epoch=0,
                               tag=sign(w, "upload", 0, payload))
            assert r["ok"], r
        n_up = cfg.needed_update_count
        for j, w in enumerate([w for w in wallets
                               if w.address in committee]):
            scores = [0.5 + 0.01 * (j + u) for u in range(n_up)]
            payload = struct.pack(f"<{n_up}d", *scores)
            r = client.request("scores", addr=w.address, epoch=0,
                               scores=scores,
                               tag=sign(w, "scores", 0, payload))
            assert r["ok"] or r.get("status") == "WRONG_EPOCH", r
    info = client.request("info")
    assert info["epoch"] == 1, info
    wall = time.perf_counter() - t_round
    if args.snapshot_interval:
        # snapshot finalization (certify -> artifact -> prefix GC) rides
        # the monitor loop — wait for the GC'd base so the scrape below
        # carries the compaction row
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            inf = client.request("info")
            if inf.get("snapshot_i") is not None and inf["log_base"]:
                break
            time.sleep(0.05)

    # the numbers ride the fleet path: one FleetCollector scrape of the
    # telemetry RPC (writer + every validator answer the same surface
    # the process-federation driver scrapes each round).  All roles
    # share this process, so the writer snapshot's trace_costs holds the
    # whole round's attribution — same data the old in-process read gave.
    coll = FleetCollector(
        {"writer": (server.host, server.port),
         **{f"validator-{i}": (v.host, v.port)
            for i, v in enumerate(nodes)}})
    scrape = coll.scrape(tag="profile_round")
    answered = scrape["coverage"]["answered"]
    expected = scrape["coverage"]["expected"]
    writer_snap = scrape["roles"].get("writer") or {}

    client.close()
    server.close()
    for v in nodes:
        v.close()

    costs = dict(writer_snap.get("trace_costs") or tracing.PROC.costs)
    phases = {
        "wire": costs.get("wire.send_s", 0) + costs.get("wire.recv_s", 0),
        "crypto": costs.get("crypto.sign_s", 0)
        + costs.get("crypto.verify_s", 0),
        "validate": costs.get("bft.validate_s", 0),
        "certify": costs.get("bft.certify_s", 0),
        "aggregate": costs.get("aggregate_s", 0),
    }
    mode = ("LEGACY (pre-PR)"
            if os.environ.get("BFLC_CONTROL_PLANE_LEGACY") else "fast")
    print(f"one federated round: {n} clients, {args.validators} "
          f"validators, quorum certification — {mode} control plane, "
          f"crypto backend: {ED25519_BACKEND}")
    print(f"round wall time: {wall * 1e3:9.1f} ms   "
          f"(log={info['log_size']} ops, "
          f"certified={info['certified_size']})")
    print(f"telemetry scrape: {answered}/{expected} roles answered")
    print(f"{'phase':<10} {'time_ms':>9}  {'share':>6}  notes")
    for name, sec in sorted(phases.items(), key=lambda kv: -kv[1]):
        note = ""
        if name == "certify":
            note = "(contains validate+crypto+wire of the vote path)"
        elif name == "crypto":
            note = (f"sign={costs.get('crypto.sign_n', 0):.0f} "
                    f"verify={costs.get('crypto.verify_n', 0):.0f} calls")
        print(f"{name:<10} {sec * 1e3:9.1f}  {sec / wall:6.1%}  {note}")
    other = ("wire.bytes_out", "wire.bytes_in")
    print("wire bytes: out={:.0f} in={:.0f}".format(
        costs.get(other[0], 0), costs.get(other[1], 0)))

    # data-plane breakdown (PR 5 obs counters): frame-encoding mix,
    # compression win, and blob-cache traffic when a router ran —
    # summed by the ONE snapshot-schema helper fleet_top renders with
    from fleet_top import _sum_counter as _csum

    frames = {k: _csum(writer_snap, "wire_frames_total", kind=k)
              for k in ("bin", "json", "zip")}
    zraw = _csum(writer_snap, "wire_zip_bytes_total", which="raw")
    zwire = _csum(writer_snap, "wire_zip_bytes_total", which="wire")
    hits = _csum(writer_snap, "dataplane_cache_events_total",
                 event="hit")
    misses = _csum(writer_snap, "dataplane_cache_events_total",
                   event="miss")
    line = (f"data plane: frames {frames['bin']:.0f}bin/"
            f"{frames['json']:.0f}json/{frames['zip']:.0f}zip")
    if zwire:
        line += (f"   compression {zraw / 1e6:.2f}->{zwire / 1e6:.2f} MB "
                 f"({zraw / zwire:.2f}x)")
    if hits or misses:
        line += f"   cache {hits:.0f}h/{misses:.0f}m"
    print(line)

    # certified snapshots + compaction (PR 7): checkpoint freshness,
    # artifact weight, and the bounded-log evidence off the same scrape
    from fleet_top import _gauge_value as _gv

    age = _gv(writer_snap, "snapshot_age_rounds")
    if age is not None and age >= 0:
        print(f"snapshots: age {int(age)}r   "
              f"{_gv(writer_snap, 'snapshot_bytes', 0) / 1e6:.2f} MB   "
              f"log base {int(_gv(writer_snap, 'log_base', 0))}   "
              f"gc {_csum(writer_snap, 'ledger_gc_ops_total'):.0f} ops")

    # async buffered aggregation (--async-buffer): the same row
    # fleet_top renders — buffer depth, staleness distribution of the
    # admitted deltas, aggregations committed
    from fleet_top import _merged_hist as _mh

    # sparse upload deltas (--delta-density): protocol density plus the
    # writer-side densify decode cost per admitted blob — the same
    # panel fleet_top renders
    dens = _gv(writer_snap, "delta_density")
    if dens is not None and dens < 1.0:
        n_sd, m_sd = _mh(writer_snap, "sparse_decode_seconds")
        print(f"sparse: density {dens:g}   decode {n_sd} blobs "
              f"(mean {m_sd * 1e3:.2f} ms)   "
              f"decode share {n_sd * m_sd / wall:.2%} of round wall")

    aggs = _csum(writer_snap, "async_aggregations_total")
    n_st, m_st = _mh(writer_snap, "async_admitted_staleness")
    if aggs or n_st:
        print(f"async: buffer {int(_gv(writer_snap, 'async_buffer_depth', 0))}"
              f"   admitted {n_st} (staleness mean {m_st:.2f} epochs)"
              f"   aggregations {aggs:.0f}"
              f"   ({aggs / wall:.1f}/s this round)")
    if snap_dir:
        import shutil
        shutil.rmtree(snap_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
